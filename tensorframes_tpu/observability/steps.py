"""Per-step training telemetry: JSONL step log + live metrics + trace.

``StepTelemetry`` is an ``on_step``-shaped callable
(``telemetry(step, metrics)``) that ``training.run_resumable`` and
``training.train_on_frame`` invoke via their ``telemetry=`` parameter.
Each call it:

* measures the wall-clock since the previous step (first step: since
  arming) and derives rows/s when the per-step row count is known —
  ``train_on_frame`` fills ``rows_per_step`` in from its batch size
  automatically;
* extracts a scalar loss from the step's metrics (a bare scalar, or a
  dict/mapping with a ``"loss"`` entry; anything else records null);
* updates the process registry: ``tftpu_train_steps_total``,
  ``tftpu_train_step_seconds``, ``tftpu_train_loss``,
  ``tftpu_train_rows_per_sec``;
* appends one JSON line to ``jsonl_path`` (when given) —
  ``{"step", "ts", "step_seconds", "loss", "rows_per_sec"}`` plus the
  additive ``run_id``/``process_index`` context stamp (multi-process
  step logs join on them) — flushed per line so a preempted run's log
  is complete up to the kill; and
* lands a ``train.step`` complete event on the trace timeline when
  tracing is enabled.

The instance is reusable across a resume: wall-clock deltas restart at
the first post-restore step instead of spanning the outage.
"""

from __future__ import annotations

import json
import time
from typing import Any, IO, Optional

import numpy as np

from . import context as _context
from . import events
from .metrics import REGISTRY, counter, gauge, histogram

__all__ = ["StepTelemetry", "extract_loss"]

_STEPS = counter(
    "tftpu_train_steps_total", "Training steps observed by StepTelemetry"
)
_STEP_SECONDS = histogram(
    "tftpu_train_step_seconds", "Wall-clock per training step (seconds)"
)
_LOSS = gauge("tftpu_train_loss", "Most recent per-step training loss")
_ROWS_PER_SEC = gauge(
    "tftpu_train_rows_per_sec", "Most recent training throughput (rows/s)"
)


def extract_loss(metrics: Any) -> Optional[float]:
    """Best-effort scalar loss from a step's metrics pytree: a mapping's
    ``"loss"`` entry, or the value itself when it is scalar-shaped.
    Returns None (→ JSON null) when no finite-arity scalar is found."""
    v = metrics
    if hasattr(metrics, "get"):
        v = metrics.get("loss")
        if v is None:
            return None
    try:
        arr = np.asarray(v)
    except (TypeError, ValueError):
        return None
    if arr.shape != () or arr.dtype == object:
        return None
    try:
        return float(arr)
    except (TypeError, ValueError):
        return None


class StepTelemetry:
    """Step-telemetry sink; pass as ``telemetry=`` to the training loops
    (or call directly from a custom loop).

    ``rows_per_step`` enables rows/s; ``train_on_frame`` sets it from
    its batch size when left None. ``registry=None`` (default) uses the
    process registry. Use as a context manager — or call :meth:`close`
    — to release the JSONL file handle deterministically."""

    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        rows_per_step: Optional[int] = None,
        registry=None,
    ):
        self.jsonl_path = jsonl_path
        self.rows_per_step = rows_per_step
        self.steps_seen = 0
        self.last_loss: Optional[float] = None
        if registry is None or registry is REGISTRY:
            self._steps = _STEPS
            self._step_seconds = _STEP_SECONDS
            self._loss = _LOSS
            self._rows_per_sec = _ROWS_PER_SEC
        else:
            self._steps = registry.counter("tftpu_train_steps_total")
            self._step_seconds = registry.histogram("tftpu_train_step_seconds")
            self._loss = registry.gauge("tftpu_train_loss")
            self._rows_per_sec = registry.gauge("tftpu_train_rows_per_sec")
        self._file: Optional[IO[str]] = None
        # the first step is charged from construction time, so its dt
        # includes jit compile + restore — a number worth seeing, and it
        # keeps every JSONL row fully populated
        self._last_t: float = time.perf_counter()

    def _sink(self) -> Optional[IO[str]]:
        if self.jsonl_path is None:
            return None
        if self._file is None or self._file.closed:
            self._file = open(self.jsonl_path, "a")
        return self._file

    def __call__(self, step: int, metrics: Any) -> None:
        now = time.perf_counter()
        dt = now - self._last_t
        self._last_t = now
        self.steps_seen += 1
        loss = extract_loss(metrics)
        self.last_loss = loss
        # a guard-tripped step hands the raw non-finite metrics through:
        # strict JSON has no NaN/Inf token, and a bare NaN would corrupt
        # the very artifacts (steps.jsonl, trace.json, registry JSONL)
        # this subsystem exports — record null and leave the gauge alone
        json_loss = loss if loss is not None and np.isfinite(loss) else None
        rows_per_sec = None
        self._steps.inc()
        self._step_seconds.observe(dt)
        if self.rows_per_step and dt > 0:
            rows_per_sec = self.rows_per_step / dt
        if json_loss is not None:
            self._loss.set(json_loss)
        if rows_per_sec is not None:
            self._rows_per_sec.set(rows_per_sec)
        if events.active():
            events.TRACER.emit_complete(
                "train.step", now - dt, dt,
                args={"step": step, "loss": json_loss},
                cat="train",
            )
        f = self._sink()
        if f is not None:
            # run_id/process_index make multi-process step logs joinable
            # (ISSUE 6 satellite) — ADDITIVE fields only: readers keyed
            # on the original five keys keep working unchanged
            f.write(json.dumps({
                "step": int(step),
                "ts": round(time.time(), 6),
                "step_seconds": round(dt, 6),
                "loss": json_loss,
                "rows_per_sec": (
                    round(rows_per_sec, 3) if rows_per_sec is not None else None
                ),
                **_context.snapshot(),
            }) + "\n")
            f.flush()

    def close(self) -> None:
        if self._file is not None and not self._file.closed:
            self._file.close()

    def __enter__(self) -> "StepTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
