"""Plan-profile reading and rendering (ISSUE 17).

Two consumers share this module:

* **EXPLAIN ANALYZE** — :func:`profile_lines` annotates a frame's plan
  tree with the per-stage profile its last execution recorded into the
  stats sidecar (``plan/stats.py``): wall, rows, bytes, chosen strategy
  and the compile-vs-run split, plus the TFG-diagnostic evidence
  (fusion barriers → TFG107, unfused epilogues → TFG109, missed
  pushdowns → TFG110) already hanging off the frame. Reached through
  ``tfs.explain_plan(df, analyze=True)`` /
  ``TensorFrame.explain(analyze=True)``.
* **``observability report --profile <sidecar-dir>``** —
  :func:`render_report` scans a sidecar directory OFFLINE (CI
  artifacts, a laptop) and renders the top-N slowest recorded plan
  stages across every fingerprint plus the current per-strategy
  observed-wall tables feeding the latency-driven ``decide_*`` flips.

Offline readers never quarantine: deleting a corrupt sidecar is the
owning process's job (``plan/stats.py`` does it on load); a report over
someone else's artifact directory must be read-only. Corrupt or alien
files are skipped and counted in the report header instead.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = [
    "load_profiles",
    "load_strategy_walls",
    "top_stages",
    "render_report",
    "profile_lines",
]


def _valid_record(rec: object, fp: str) -> bool:
    # mirrors plan/stats._valid, minus the version pin: a report over
    # an older artifact should still render what it can
    return (
        isinstance(rec, dict)
        and rec.get("fp") == fp
        and isinstance(rec.get("execs"), int)
    )


def load_profiles(sidecar_dir: str) -> Tuple[Dict[str, dict], int]:
    """All readable per-fingerprint records under ``sidecar_dir``
    (``{fp: record}``), plus the count of skipped (corrupt / alien /
    mis-named) files. Never raises, never deletes."""
    records: Dict[str, dict] = {}
    skipped = 0
    try:
        names = sorted(os.listdir(sidecar_dir))
    except OSError:
        return records, 0
    for name in names:
        if not name.endswith(".json") or name == "strategy_walls.json":
            continue
        fp = name[: -len(".json")]
        try:
            with open(os.path.join(sidecar_dir, name), "r") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            skipped += 1
            continue
        if not _valid_record(rec, fp):
            skipped += 1
            continue
        records[fp] = rec
    return records, skipped


def load_strategy_walls(sidecar_dir: str) -> Dict[str, dict]:
    """The per-(decision, strategy) observed-wall tables from
    ``strategy_walls.json`` (``{decision: {"obs", "strategies"}}``), or
    ``{}`` when absent/unreadable. Read-only — see module docstring."""
    path = os.path.join(sidecar_dir, "strategy_walls.json")
    try:
        with open(path, "r") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return {}
    if not (
        isinstance(rec, dict)
        and rec.get("kind") == "strategy_walls"
        and isinstance(rec.get("tables"), dict)
    ):
        return {}
    return rec["tables"]


def top_stages(records: Dict[str, dict], n: int = 10) -> List[dict]:
    """The ``n`` slowest recorded plan stages across every fingerprint,
    slowest first. Each row is the sidecar profile entry plus its
    ``fp``."""
    rows: List[dict] = []
    for fp, rec in records.items():
        prof = rec.get("profile")
        if not isinstance(prof, list):
            continue
        for entry in prof:
            if isinstance(entry, dict) and "stage" in entry:
                rows.append({"fp": fp, **entry})
    rows.sort(key=lambda r: -float(r.get("wall_s", 0.0) or 0.0))
    return rows[: max(0, int(n))]


def _fmt_stage(entry: dict, *, with_fp: bool = False) -> str:
    parts = [f"{entry.get('stage', '?')}"]
    wall = entry.get("wall_s")
    if wall is not None:
        parts.append(f"wall={float(wall):.6f}s")
    if entry.get("strategy"):
        parts.append(f"strategy={entry['strategy']}")
    if entry.get("rows") is not None:
        parts.append(f"rows={int(entry['rows'])}")
    if entry.get("bytes") is not None:
        parts.append(f"bytes={int(entry['bytes'])}")
    if entry.get("compile_s") is not None:
        parts.append(f"compile={float(entry['compile_s']):.6f}s")
    if with_fp and entry.get("fp"):
        parts.append(f"fp={entry['fp'][:12]}")
    return "  ".join(parts)


def render_report(sidecar_dir: str, top: int = 10) -> str:
    """The ``report --profile`` body: top-N slowest stages + the
    per-strategy wall tables, as one printable string."""
    records, skipped = load_profiles(sidecar_dir)
    lines = [
        f"# plan-profile sidecar: {sidecar_dir} — "
        f"{len(records)} fingerprint(s)"
        + (f", {skipped} unreadable file(s) skipped" if skipped else "")
    ]
    stages = top_stages(records, n=top)
    lines.append(f"\n# top {len(stages)} slowest recorded plan stage(s)")
    if stages:
        for entry in stages:
            lines.append("  " + _fmt_stage(entry, with_fp=True))
    else:
        lines.append("  (no per-stage profiles recorded)")
    walls = load_strategy_walls(sidecar_dir)
    lines.append("\n# observed per-strategy walls (EWMA seconds)")
    if walls:
        for decision in sorted(walls):
            table = walls[decision]
            strategies = table.get("strategies", {})
            lines.append(
                f"  {decision} (obs={int(table.get('obs', 0))}):"
            )
            for strat in sorted(strategies):
                ent = strategies[strat]
                lines.append(
                    f"    {strat:<24} ewma={float(ent.get('ewma_s', 0.0)):.6f}s"
                    f"  n={int(ent.get('n', 0))}"
                )
    else:
        lines.append("  (no strategy walls recorded)")
    return "\n".join(lines)


def profile_lines(frame) -> List[str]:
    """EXPLAIN ANALYZE annotation lines for one frame: the recorded
    per-stage profile keyed by the frame's plan fingerprint, the
    counted decisions' latency evidence, and the TFG cross-references.
    Imports the plan layer lazily — this module must stay loadable
    offline without touching jax."""
    from ..plan import ir as _ir
    from ..plan import stats as _stats

    node = getattr(frame, "_plan", None)
    fp = getattr(frame, "_plan_fp", None)  # stashed at force time —
    # the plan chain itself is dropped once the blocks materialize
    if node is None and fp is None:
        return [
            "profile: frame carries no plan chain and no recorded "
            "execution fingerprint"
        ]
    if not _stats.reopt_enabled():
        return [
            "profile: unavailable — adaptive stats are off "
            "(TFTPU_REOPT=0 or plan_reopt=False)"
        ]
    if fp is None:
        source, nodes = _ir.resolve_chain(node)
        fp = _stats.chain_fingerprint(source, nodes)
    rec = _stats.lookup(fp)
    lines: List[str] = []
    if rec is None:
        return [
            f"profile: fp={fp} — no recorded execution "
            "(force the frame, then explain again)"
        ]
    head = f"profile: fp={fp}  execs={int(rec.get('execs', 0))}"
    if rec.get("wall_s") is not None:
        head += f"  wall={float(rec['wall_s']):.6f}s"
    lines.append(head)
    prof = rec.get("profile")
    if isinstance(prof, list) and prof:
        for entry in prof:
            if isinstance(entry, dict):
                lines.append("  " + _fmt_stage(entry))
    else:
        lines.append("  (no per-stage breakdown recorded yet)")
    # observed join selectivities / pushdown history already recorded
    joins = rec.get("joins")
    if isinstance(joins, dict) and joins:
        for key in sorted(joins):
            obs = joins[key]
            if isinstance(obs, dict):
                kv = "  ".join(
                    f"{k}={obs[k]}" for k in sorted(obs)
                )
                lines.append(f"  join[{key}]: {kv}")
    push = rec.get("push")
    if isinstance(push, dict) and push:
        kv = "  ".join(f"{k}={push[k]}" for k in sorted(push))
        lines.append(f"  pushdown: {kv}")
    # TFG cross-references: the lint rules' evidence, named inline so
    # the profile points straight at the fix
    try:
        _n_maps, barriers = _ir.chain_barriers(frame)
    except Exception:
        barriers = []
    for b in barriers:
        lines.append(
            f"  TFG107 fusion-barrier: {b.get('reason', '?')}"
        )
    for u in _ir.unfused_epilogues(frame):
        lines.append(
            "  TFG109 unfused-aggregate: "
            f"{u.get('verb', '?')} — {u.get('reason', '?')}"
        )
    for m in _ir.pushdown_miss_log(frame):
        lines.append(
            f"  TFG110 missed-pushdown: {m.get('detail', m)}"
        )
    return lines
