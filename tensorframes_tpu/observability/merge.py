"""Cross-process trace aggregation: per-process shards → one timeline.

A MULTICHIP-style multi-process run produces one trace shard per
process (``events.save_shard``), each on its own monotonic clock and
each claiming ``pid = os.getpid()``. This module merges them into one
valid Chrome/Perfetto trace with **per-process tracks**:

* every shard's events are re-stamped with ``pid = process_index`` (the
  stable rank from the shard's ``otherData``), so Perfetto renders one
  process group per rank regardless of what OS pids the fleet drew;
* each shard's timestamps are shifted by the difference of its
  wall-clock epoch anchor (``trace_epoch_unix_us``) against the
  earliest shard's, so "process 3 stalled while process 0 compiled"
  reads off one shared real-time axis (wall clocks are NTP-grade
  aligned within a pod — microsecond-perfect alignment is not claimed,
  and sub-ms skew is irrelevant at dispatch timescales);
* ``process_name`` / ``process_sort_index`` metadata events label and
  order the tracks;
* shards from *different* runs refuse to merge (mismatched ``run_id``)
  unless forced — silently interleaving two runs' timelines is how
  postmortems go wrong.

``observability merge`` (cli.py) is the command-line face of
:func:`merge_traces`.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence

from ..utils import get_logger

logger = get_logger(__name__)

__all__ = ["merge_traces", "load_shard", "find_shards"]


def find_shards(directory: str, run_id: Optional[str] = None) -> List[str]:
    """Shard files under ``directory`` (the ``events.save_shard``
    naming), optionally restricted to one run id, sorted by rank."""
    pat = f"trace_{run_id}_p*.json" if run_id else "trace_*_p*.json"
    paths = _glob.glob(os.path.join(directory, pat))

    def rank(p: str) -> int:
        stem = os.path.basename(p).rsplit(".", 1)[0]
        try:
            return int(stem.rsplit("_p", 1)[1])
        except (IndexError, ValueError):
            return 1 << 30
    return sorted(paths, key=rank)


def load_shard(path: str) -> Dict[str, Any]:
    """Read one shard; raises ValueError on non-trace JSON."""
    with open(path) as f:
        shard = json.load(f)
    if not isinstance(shard, dict) or "traceEvents" not in shard:
        raise ValueError(
            f"{path}: not a Chrome trace (no traceEvents key)"
        )
    return shard


def _shard_meta(shard: Dict[str, Any], path: str, fallback_index: int):
    other = shard.get("otherData") or {}
    idx = other.get("process_index")
    if idx is None:
        # pre-correlation shard (or foreign trace): fall back to file
        # order, loudly — tracks still separate, identity is best-effort
        logger.warning(
            "merge: %s carries no process_index; assigning track %d by "
            "file order", path, fallback_index,
        )
        idx = fallback_index
    if not other.get("trace_epoch_unix_us"):
        logger.warning(
            "merge: %s carries no wall-clock epoch anchor; its events "
            "keep their own timebase (placed at the start of the merged "
            "axis) — cross-process ordering against this shard is not "
            "meaningful", path,
        )
    return {
        "index": int(idx),
        "run_id": other.get("run_id"),
        "pid": other.get("pid"),
        "epoch_us": other.get("trace_epoch_unix_us"),
        "dropped": int(other.get("dropped_events") or 0),
    }


def merge_traces(
    paths: Sequence[str], force: bool = False
) -> Dict[str, Any]:
    """Merge per-process trace shards into one Chrome trace object.

    ``paths`` are shard files (``events.save_shard`` layout or any
    Chrome trace carrying the ``otherData`` context stamp). Returns the
    merged ``{"traceEvents": [...], ...}`` dict; :func:`json.dump` it or
    hand it to Perfetto. ``force=True`` merges across mismatched
    run_ids (tracks are still separated; the metadata records every id).
    """
    if not paths:
        raise ValueError("merge_traces: no shard paths given")
    shards = []
    for i, p in enumerate(paths):
        shard = load_shard(p)
        shards.append((p, shard, _shard_meta(shard, p, i)))

    run_ids = sorted({m["run_id"] for _, _, m in shards if m["run_id"]})
    if len(run_ids) > 1 and not force:
        raise ValueError(
            "merge_traces: shards come from different runs "
            f"{run_ids} — pass force=True to merge anyway"
        )
    seen_ranks: Dict[int, str] = {}
    for p, _, m in shards:
        if m["index"] in seen_ranks and not force:
            raise ValueError(
                f"merge_traces: duplicate process_index {m['index']} "
                f"({seen_ranks[m['index']]} and {p}) — a stale shard "
                "from an earlier run? pass force=True to keep both"
            )
        seen_ranks.setdefault(m["index"], p)

    anchors = [m["epoch_us"] for _, _, m in shards if m["epoch_us"]]
    base_us = min(anchors) if anchors else 0

    merged: List[Dict[str, Any]] = []
    processes = []
    total_dropped = 0
    for _, shard, m in shards:
        idx = m["index"]
        shift = (m["epoch_us"] - base_us) if m["epoch_us"] else 0
        label = f"process {idx}"
        if m["pid"]:
            label += f" (pid {m['pid']})"
        merged.append({
            "ph": "M", "name": "process_name", "pid": idx,
            "args": {"name": label},
        })
        merged.append({
            "ph": "M", "name": "process_sort_index", "pid": idx,
            "args": {"sort_index": idx},
        })
        for ev in shard["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = idx
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            merged.append(ev)
        total_dropped += m["dropped"]
        processes.append({
            "process_index": idx,
            "pid": m["pid"],
            "events": len(shard["traceEvents"]),
            "epoch_unix_us": m["epoch_us"],
        })

    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "tensorframes_tpu.observability.merge",
            "run_id": run_ids[0] if len(run_ids) == 1 else run_ids,
            "num_shards": len(shards),
            "processes": processes,
            "dropped_events": total_dropped,
        },
    }
