"""Trace context: the (run_id, process_index) identity of this process.

Every ROADMAP direction after PR 2 is multi-process (sharded AOT,
out-of-core shuffle, serving), and a fleet of per-process telemetry
files is unmergeable unless each one says which *run* it belongs to and
which *process* wrote it. This module owns that identity:

* ``run_id()`` — one id per logical run, shared by every process of a
  multi-process launch. ``TFTPU_RUN_ID`` wins (the launcher exports it
  to the whole fleet); otherwise a random 12-hex id is minted once per
  process. A parent forking workers calls :func:`child_env` to hand
  them its id.
* ``process_index()`` — this process's rank. Resolution order: an
  explicit :func:`bind` (``parallel.distributed.init_distributed``
  binds the JAX process id after the coordinator handshake) >
  ``TFTPU_PROCESS_INDEX`` > ``JAX_PROCESS_ID`` > ``jax.process_index()``
  when a backend is already live > 0. The env fallbacks matter for
  plain ``fork``/``spawn`` fleets (the MULTICHIP dryrun shape) that
  never touch ``jax.distributed``.

The context is stamped onto every exported telemetry artifact: trace
shards (``events.save``/``save_shard`` metadata), metrics JSONL rows,
step-log lines, and flight-recorder records — which is what makes the
``observability merge`` aggregator able to reassemble one timeline from
a MULTICHIP-style run.
"""

from __future__ import annotations

import os
import sys
import threading
import uuid
from typing import Dict, Optional

__all__ = [
    "run_id",
    "set_run_id",
    "process_index",
    "num_processes",
    "bind",
    "snapshot",
    "child_env",
    "TRACE_HEADER",
    "bind_request",
    "clear_request",
    "current_request",
    "request_scope",
    "trace_header_value",
    "parse_trace_header",
]

_lock = threading.Lock()
_run_id: Optional[str] = None
_process_index: Optional[int] = None
_num_processes: Optional[int] = None


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    if v is None or v == "":
        return None
    try:
        return int(v)
    except ValueError:
        return None


def run_id() -> str:
    """The logical run id (stable for the life of this process)."""
    global _run_id
    with _lock:
        if _run_id is None:
            _run_id = os.environ.get("TFTPU_RUN_ID") or uuid.uuid4().hex[:12]
        return _run_id


def set_run_id(rid: str) -> None:
    """Pin the run id (launchers that mint their own ids)."""
    global _run_id
    if not rid:
        raise ValueError("run_id must be non-empty")
    with _lock:
        _run_id = str(rid)


def _jax_index_if_live() -> Optional[int]:
    """jax's process index, ONLY if a backend is already initialized.
    ``jax.process_index()`` would happily initialize the backend as a
    side effect — a telemetry stamp written before the coordinator
    handshake must never do that (it would pin the process to a
    single-process rank-0 backend right before init_distributed tries
    the real multi-process init). When the liveness probe is
    unavailable, the answer is None, not a gamble."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return None
    except Exception:
        return None  # probe moved: never risk triggering init
    try:
        return int(jax.process_index())
    except Exception:
        return None


def process_index() -> int:
    """This process's rank within the run (0 on single-process runs)."""
    with _lock:
        if _process_index is not None:
            return _process_index
    idx = _env_int("TFTPU_PROCESS_INDEX")
    if idx is None:
        idx = _env_int("JAX_PROCESS_ID")
    if idx is None:
        idx = _jax_index_if_live()
    return idx if idx is not None else 0


def num_processes() -> Optional[int]:
    """Process count of the run, when known (None otherwise)."""
    with _lock:
        if _num_processes is not None:
            return _num_processes
    return _env_int("TFTPU_NUM_PROCESSES") or _env_int("JAX_NUM_PROCESSES")


def bind(
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
    run_id: Optional[str] = None,
) -> None:
    """Authoritatively set context fields (``init_distributed`` calls
    this after the coordinator handshake; tests and custom launchers may
    too). ``None`` fields are left as-is."""
    global _process_index, _num_processes, _run_id
    with _lock:
        if process_index is not None:
            _process_index = int(process_index)
        if num_processes is not None:
            _num_processes = int(num_processes)
        if run_id is not None:
            _run_id = str(run_id)


def snapshot() -> Dict[str, object]:
    """``{"run_id", "process_index"}`` — the stamp every telemetry
    exporter attaches."""
    return {"run_id": run_id(), "process_index": process_index()}


def child_env(index: Optional[int] = None) -> Dict[str, str]:
    """Env vars a launcher hands a forked/spawned worker so its shards
    join this run: the shared ``TFTPU_RUN_ID`` plus (when ``index`` is
    given) the worker's ``TFTPU_PROCESS_INDEX``."""
    env = {"TFTPU_RUN_ID": run_id()}
    if index is not None:
        env["TFTPU_PROCESS_INDEX"] = str(int(index))
    return env


# ---------------------------------------------------------------------------
# cross-hop request tracing (ISSUE 17): one request id from Router
# ingress through a remote replica's batcher flush
# ---------------------------------------------------------------------------

#: HTTP header carrying ``<request_id>;run=<run_id>`` across the
#: Router → replica hop. The request id is the Router's idempotency key
#: — STABLE across a redrive, so a redriven request still shows as one
#: id in the merged timeline.
TRACE_HEADER = "X-Tftpu-Trace"

_request_tls = threading.local()


def bind_request(request_id: Optional[str]) -> None:
    """Bind the current thread's request id (None unbinds). The serving
    layer binds at submit/dispatch and stamps the id into every trace
    span it emits on this thread; batcher/decode threads carry it via
    the explicit per-request slots instead (one flush serves many
    requests — a thread-local could only name one)."""
    _request_tls.request_id = request_id or None


def clear_request() -> None:
    _request_tls.request_id = None


def current_request() -> Optional[str]:
    """The request id bound to this thread, or None."""
    return getattr(_request_tls, "request_id", None)


class request_scope:
    """``with request_scope(rid):`` — bind/restore around one request's
    handling on this thread (exception-safe)."""

    def __init__(self, request_id: Optional[str]):
        self._rid = request_id

    def __enter__(self):
        self._prev = current_request()
        bind_request(self._rid)
        return self._rid

    def __exit__(self, *exc):
        bind_request(self._prev)
        return False


def trace_header_value(request_id: str) -> str:
    """Serialize the trace context the Router stamps onto the hop."""
    return f"{request_id};run={run_id()}"


def parse_trace_header(value: Optional[str]):
    """``(request_id, run_id)`` from a received header value; both None
    when the header is absent/garbled (tracing degrades to per-process
    timelines, never to an error — telemetry must not fail a request)."""
    if not value or not isinstance(value, str) or len(value) > 256:
        return None, None
    head, _, rest = value.partition(";")
    rid = head.strip() or None
    run = None
    for part in rest.split(";"):
        k, _, v = part.partition("=")
        if k.strip() == "run" and v.strip():
            run = v.strip()
    return rid, run


def _reset_for_tests() -> None:
    """Forget bound/minted context (test hygiene only)."""
    global _run_id, _process_index, _num_processes
    with _lock:
        _run_id = None
        _process_index = None
        _num_processes = None
    clear_request()


def _after_fork_in_child() -> None:
    # a parent-bound rank is wrong in a forked worker: drop it so the
    # child re-resolves from ITS env (fork launchers set
    # TFTPU_PROCESS_INDEX per child); the minted run_id is kept — the
    # fork family IS one run. No lock: the child is single-threaded at
    # this instant, and the parent's lock state is unreliable here.
    global _process_index
    _process_index = None


if hasattr(os, "register_at_fork"):  # pragma: no branch - posix
    os.register_at_fork(after_in_child=_after_fork_in_child)
