"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The reference shipped log4j levels and nothing else (SURVEY §5); the
resilience layer (PR 1) then added retries, NaN guards, and checkpoint
verification that all ran blind. This module is the numbers side of the
observability subsystem: every instrumented layer (executor jit cache,
prefetch queue, checkpoint IO, retry/guard/fault paths, training steps)
registers its instruments here at import time, so an exposition always
carries the full catalog — a counter that never fired reads 0, it does
not vanish.

Exporters:

* ``REGISTRY.to_prometheus()`` — Prometheus text exposition format
  (0.0.4), histograms as cumulative ``_bucket{le=...}`` series plus
  ``_sum``/``_count``.
* ``REGISTRY.to_jsonl()`` / ``write_jsonl(path)`` — one JSON object per
  metric per line, for offline diffing and the CI artifact.
* ``metrics_server(port)`` — a daemon-thread HTTP server exposing
  ``/metrics`` (Prometheus) and ``/metrics.json`` (JSONL) for scraping.

All instruments are thread-safe (one registry-wide lock; updates are a
few dict/float ops, far cheaper than the host-side IO they count).
``reset()`` zeroes values but keeps registrations — instrumented modules
hold direct references to their instruments, so tests can zero the world
without orphaning them.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "quantile_from_cumulative",
    "counter",
    "gauge",
    "histogram",
    "metrics_server",
]

#: Default histogram bucket upper bounds (seconds-flavored: spans the
#: sub-millisecond dispatch regime through multi-minute TPU compiles).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

LabelPairs = Tuple[Tuple[str, str], ...]


def _label_pairs(labels: Optional[Mapping[str, str]]) -> LabelPairs:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr."""
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Metric:
    """Shared identity: name + static label set + help text. Subclasses
    hold the value(s); all mutation goes through the registry lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: LabelPairs, lock):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = lock

    @property
    def label_str(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in self.labels
        )
        return "{" + inner + "}"

    def _zero(self) -> None:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count (decreasing is a bug)."""

    kind = "counter"

    def __init__(self, name, help, labels, lock):
        super().__init__(name, help, labels, lock)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _zero(self) -> None:
        self._value = 0.0

    def _samples(self):
        return [(self.name, self.label_str, self._value)]

    def _json_value(self):
        return {"value": self._value}


class Gauge(_Metric):
    """Point-in-time level (queue depth, loss, rows/s)."""

    kind = "gauge"

    def __init__(self, name, help, labels, lock):
        super().__init__(name, help, labels, lock)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _zero(self) -> None:
        self._value = 0.0

    def _samples(self):
        return [(self.name, self.label_str, self._value)]

    def _json_value(self):
        return {"value": self._value}


class Histogram(_Metric):
    """Fixed-bucket histogram: per-bucket counts (non-cumulative inside;
    cumulative on exposition, per the Prometheus convention) + sum +
    count. Bucket bounds are upper-inclusive; values above the last
    bound land in the implicit ``+Inf`` bucket."""

    kind = "histogram"

    def __init__(self, name, help, labels, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labels, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name}: buckets must be non-empty")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # + the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007 — short lists
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)], ending with (+Inf, count)."""
        with self._lock:
            out, running = [], 0
            for b, c in zip(self.buckets, self._counts):
                running += c
                out.append((b, running))
            out.append((float("inf"), self._count))
            return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the ``q``-quantile (0 < q < 1) from bucket counts by
        linear interpolation within the target bucket — the same
        estimate Prometheus's ``histogram_quantile`` makes. Values in
        the +Inf bucket clamp to the largest finite bound (the honest
        answer a bounded histogram can give). None when empty."""
        if not (0.0 < q < 1.0):
            raise ValueError(f"quantile q must be in (0, 1), got {q}")
        cum = self.cumulative()
        return quantile_from_cumulative(cum, cum[-1][1], q)

    def quantiles(
        self, qs: Sequence[float] = (0.5, 0.95, 0.99)
    ) -> Dict[str, Optional[float]]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` via :meth:`quantile`."""
        return {f"p{int(q * 100)}": self.quantile(q) for q in qs}

    def _zero(self) -> None:
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def _samples(self):
        with self._lock:
            cum = []
            running = 0
            for b, c in zip(self.buckets, self._counts):
                running += c
                cum.append((b, running))
            cum.append((float("inf"), self._count))
            total_sum, total_count = self._sum, self._count
        out = []
        for le, c in cum:
            ls = self.label_str
            le_pair = f'le="{_fmt(le)}"'
            merged = ls[:-1] + "," + le_pair + "}" if ls else "{" + le_pair + "}"
            out.append((self.name + "_bucket", merged, c))
        out.append((self.name + "_sum", self.label_str, total_sum))
        out.append((self.name + "_count", self.label_str, total_count))
        return out

    def _json_value(self):
        return {
            "buckets": {_fmt(le): c for le, c in self.cumulative()},
            "sum": self.sum,
            "count": self.count,
        }


def quantile_from_cumulative(
    cum: Sequence[Tuple[float, int]], count: int, q: float
) -> Optional[float]:
    """The one bucket-interpolation quantile estimate: ``cum`` is
    ``[(upper_bound, cumulative_count), ...]`` sorted ascending (a
    trailing +Inf entry is allowed and ignored — overflow clamps to the
    largest finite bound). Shared by :meth:`Histogram.quantile` and the
    offline registry-JSONL reader (observability/snapshot.py), so the
    live and exported estimates can never diverge."""
    if count <= 0:
        return None
    finite = [(b, c) for b, c in cum if b != float("inf")]
    if not finite:
        return None
    rank = q * count
    prev_bound, prev_cum = 0.0, 0
    for bound, c in finite:
        if c >= rank:
            in_bucket = c - prev_cum
            if in_bucket <= 0:  # pragma: no cover - defensive
                return bound
            frac = (rank - prev_cum) / in_bucket
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, c
    return finite[-1][0]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create store of named instruments, keyed by
    (name, sorted label pairs). Same name across label sets must keep
    one kind — Prometheus rejects mixed-type metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelPairs], _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Optional[Mapping[str, str]], **kwargs):
        key = (name, _label_pairs(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"requested {cls.kind}"
                    )
                return m
            for (other, _), existing in self._metrics.items():
                if other == name and existing.kind != cls.kind:
                    raise ValueError(
                        f"metric family {name!r} is {existing.kind}; cannot "
                        f"add a {cls.kind} series to it"
                    )
            m = cls(name, help, _label_pairs(labels), self._lock, **kwargs)
            self._metrics[key] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Mapping[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Mapping[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Mapping[str, str]] = None,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    def collect(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- lifecycle ----------------------------------------------------------

    def reset(self) -> None:
        """Zero every instrument, keep registrations (instrumented modules
        hold references; removing them would orphan live instruments)."""
        with self._lock:
            for m in self._metrics.values():
                m._zero()

    def unregister_matching(self, prefix: str) -> int:
        """Drop metrics whose name starts with ``prefix`` (test hygiene
        for registry-shape tests; production code never calls this)."""
        with self._lock:
            doomed = [k for k in self._metrics if k[0].startswith(prefix)]
            for k in doomed:
                del self._metrics[k]
            return len(doomed)

    # -- exporters ----------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """One plain dict per metric (labels + kind + values) — the JSONL
        rows, pre-serialization."""
        out = []
        for m in self.collect():
            d = {"name": m.name, "kind": m.kind, "labels": dict(m.labels)}
            d.update(m._json_value())
            out.append(d)
        return sorted(out, key=lambda d: (d["name"], sorted(d["labels"].items())))

    def to_jsonl(self) -> str:
        # rows carry the run/process identity (additive fields), so a
        # fleet's per-process metrics files are joinable offline the
        # same way trace shards are
        from . import context as _context

        ts = time.time()
        stamp = _context.snapshot()
        return "\n".join(
            json.dumps({**d, **stamp, "ts": round(ts, 3)}, sort_keys=True)
            for d in self.snapshot()
        ) + ("\n" if self._metrics else "")

    def write_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl())

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4): families grouped,
        one HELP/TYPE header per name, samples sorted for stable diffs."""
        families: Dict[str, List[_Metric]] = {}
        for m in self.collect():
            families.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name in sorted(families):
            members = families[name]
            help_text = next((m.help for m in members if m.help), "")
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {members[0].kind}")
            for m in sorted(members, key=lambda m: m.labels):
                for sample_name, label_str, v in m._samples():
                    lines.append(f"{sample_name}{label_str} {_fmt(v)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def summary_lines(self, include_zero: bool = False) -> List[str]:
        """Compact ``name{labels}=value`` lines (histograms as
        count/sum/mean) — what ``bench.py`` dumps as ``# obs |`` comment
        rows. Zero-valued instruments are skipped unless asked for."""
        out = []
        for m in self.collect():
            if isinstance(m, Histogram):
                if m.count == 0 and not include_zero:
                    continue
                mean = m.sum / m.count if m.count else 0.0
                out.append(
                    f"{m.name}{m.label_str} count={m.count} "
                    f"sum={m.sum:.6f} mean={mean:.6f}"
                )
            else:
                if m.value == 0 and not include_zero:
                    continue
                out.append(f"{m.name}{m.label_str}={_fmt(m.value)}")
        return sorted(out)


#: The process-wide default registry every instrumented module uses.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "",
            labels: Optional[Mapping[str, str]] = None) -> Counter:
    """Get-or-create a counter on the default registry."""
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "",
          labels: Optional[Mapping[str, str]] = None) -> Gauge:
    """Get-or-create a gauge on the default registry."""
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "",
              labels: Optional[Mapping[str, str]] = None,
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    """Get-or-create a histogram on the default registry."""
    return REGISTRY.histogram(name, help, labels, buckets=buckets)


def metrics_server(port: int = 9464, registry: Optional[MetricsRegistry] = None,
                   addr: str = "127.0.0.1"):
    """Serve ``/metrics`` (Prometheus text) and ``/metrics.json`` (JSONL)
    from a daemon thread. ``port=0`` binds an ephemeral port — read it
    back from ``server.server_address[1]``. Returns the
    ``ThreadingHTTPServer``; call ``.shutdown()`` to stop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] in ("/metrics.json", "/metrics.jsonl"):
                body = reg.to_jsonl().encode()
                ctype = "application/x-ndjson"
            elif self.path.split("?")[0] in ("/", "/metrics"):
                body = reg.to_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapers must not spam stderr
            pass

    server = ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(
        target=server.serve_forever, daemon=True, name="tfs-metrics-server"
    )
    t.start()
    return server
