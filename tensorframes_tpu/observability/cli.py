"""``python -m tensorframes_tpu.observability`` — report / merge / diff.

The operational face of the observability layer:

* ``report <artifact>`` — human-readable summary of any telemetry
  artifact this repo produces (bench snapshot, ``BENCH_r*.json`` round,
  bench stdout, or a metrics-registry JSONL export), with latency
  quantiles derived where histograms are present.
  ``report --profile <sidecar-dir>`` instead renders the plan-profile
  sidecars (``plan/stats.py``): top-N slowest recorded plan stages
  across all fingerprints + the per-strategy observed-wall tables
  feeding the latency-driven ``decide_*`` flips.
* ``merge -o merged.json <shards...>`` — combine per-process trace
  shards (``events.save_shard``) from a multi-process run into one
  JSON-valid Chrome/Perfetto trace with per-process tracks. ``--dir``
  globs a shard directory instead of listing files.
* ``diff <old> <new>`` — per-metric perf comparison; exits **1** when
  any metric moved against its direction past its threshold (``--
  warn-only`` downgrades to exit 0 for noisy CPU CI runners).

All subcommands run offline on files — no accelerator, no backend init,
usable on a laptop against CI artifacts.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import merge as _merge
from . import snapshot as _snapshot

__all__ = ["main"]


def _cmd_report(args) -> int:
    if args.profile:
        from . import profile as _profile

        print(_profile.render_report(args.profile, top=args.top))
        return 0
    if not args.path:
        print("report: pass an artifact path or --profile <sidecar-dir>",
              file=sys.stderr)
        return 2
    metrics, meta = _snapshot.load_metrics(args.path)
    print(f"# source: {meta.get('source')} ({args.path})")
    if not metrics:
        print("no metrics found")
        return 1
    latency = {k: v for k, v in metrics.items() if k.startswith("latency.")}
    plain = {k: v for k, v in metrics.items() if not k.startswith("latency.")}
    width = max(len(k) for k in metrics)
    for k in sorted(plain):
        print(f"{k:<{width}}  {plain[k]:g}")
    if latency:
        print("\n# latency quantiles (seconds)")
        for k in sorted(latency):
            print(f"{k:<{width}}  {latency[k]:.6f}")
    return 0


def _cmd_merge(args) -> int:
    paths: List[str] = list(args.shards)
    if args.dir:
        paths.extend(_merge.find_shards(args.dir, run_id=args.run_id))
    if not paths:
        print("merge: no shards given (pass files or --dir)", file=sys.stderr)
        return 2
    try:
        merged = _merge.merge_traces(paths, force=args.force)
    except ValueError as e:
        print(f"merge: {e}", file=sys.stderr)
        return 2
    with open(args.output, "w") as f:
        json.dump(merged, f)
    other = merged["otherData"]
    print(
        f"merged {other['num_shards']} shard(s), "
        f"{len(merged['traceEvents'])} events, run_id={other['run_id']} "
        f"→ {args.output} (open in https://ui.perfetto.dev)"
    )
    return 0


def _parse_per_metric(pairs: List[str]) -> dict:
    out = {}
    for p in pairs:
        name, _, val = p.partition("=")
        try:
            out[name] = float(val)
        except ValueError:
            val = ""
        if not name or not val:
            raise SystemExit(
                f"--metric expects NAME=THRESHOLD (numeric), got {p!r}"
            )
    return out


def _cmd_diff(args) -> int:
    old, old_meta = _snapshot.load_metrics(args.old)
    new, new_meta = _snapshot.load_metrics(args.new)
    result = _snapshot.diff_metrics(
        old, new, threshold=args.threshold,
        per_metric=_parse_per_metric(args.metric),
    )
    if args.json:
        json.dump(result, sys.stdout, indent=1)
        print()
    else:
        print(
            f"# old: {old_meta.get('source')} ({args.old}) — "
            f"{len(old)} metrics"
        )
        print(
            f"# new: {new_meta.get('source')} ({args.new}) — "
            f"{len(new)} metrics"
        )
        interesting = [
            r for r in result["rows"]
            if r["status"] in ("regression", "improvement")
            or args.all
        ]
        if interesting:
            w = max(len(r["metric"]) for r in interesting)
            for r in interesting:
                ratio = (
                    f"{r['ratio']:.3f}x" if r["ratio"] is not None else "-"
                )
                print(
                    f"{r['status']:<12} {r['metric']:<{w}} "
                    f"old={r['old']:g} new={r['new']:g} {ratio} "
                    f"({r['direction']} is better, thr ±{r['threshold']:g})"
                )
        for name in result["only_old"]:
            print(f"removed      {name}")
        for name in result["only_new"]:
            print(f"added        {name}")
        n_reg = len(result["regressions"])
        n_imp = len(result["improvements"])
        compared = len(result["rows"])
        print(
            f"# compared {compared} common metric(s): "
            f"{n_reg} regression(s), {n_imp} improvement(s)"
        )
    if result["regressions"]:
        if args.warn_only:
            print("# warn-only: regressions reported, exit 0")
            return 0
        return 1
    if not result["rows"]:
        # zero overlap usually means a broken/errored bench run or a
        # metric-name drift — a usage error worth failing on, EXCEPT
        # under --warn-only, whose contract is "never block the build"
        print("diff: no common metrics between the two inputs",
              file=sys.stderr)
        return 0 if args.warn_only else 2
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tensorframes_tpu.observability",
        description=__doc__.split("\n\n")[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    rp = sub.add_parser(
        "report", help="summarize a telemetry artifact (metrics + quantiles)"
    )
    rp.add_argument("path", nargs="?",
                    help="snapshot / BENCH_r*.json / bench stdout "
                         "/ metrics JSONL")
    rp.add_argument("--profile", metavar="SIDECAR_DIR",
                    help="render plan-profile sidecars instead: top-N "
                         "slowest recorded stages + per-strategy "
                         "observed-wall tables")
    rp.add_argument("--top", type=int, default=10,
                    help="with --profile: how many stages (default "
                         "%(default)s)")
    rp.set_defaults(fn=_cmd_report)

    mp = sub.add_parser(
        "merge", help="merge per-process trace shards into one Chrome trace"
    )
    mp.add_argument("shards", nargs="*", help="shard files "
                                              "(events.save_shard layout)")
    mp.add_argument("--dir", help="directory to glob trace_*_p*.json from")
    mp.add_argument("--run-id", help="with --dir: only this run's shards")
    mp.add_argument("-o", "--output", required=True, help="merged trace path")
    mp.add_argument("--force", action="store_true",
                    help="merge despite run_id mismatches / duplicate ranks")
    mp.set_defaults(fn=_cmd_merge)

    dp = sub.add_parser(
        "diff", help="compare two bench artifacts; exit 1 on regression"
    )
    dp.add_argument("old", help="baseline artifact")
    dp.add_argument("new", help="candidate artifact")
    dp.add_argument("--threshold", type=float,
                    default=_snapshot.DEFAULT_THRESHOLD,
                    help="relative move that counts as a regression "
                         "(default %(default)s)")
    dp.add_argument("--metric", action="append", default=[],
                    metavar="NAME=THR",
                    help="per-metric threshold override (repeatable)")
    dp.add_argument("--warn-only", action="store_true",
                    help="report regressions but exit 0 (noisy CI runners)")
    dp.add_argument("--all", action="store_true",
                    help="print every compared metric, not just movers")
    dp.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    dp.set_defaults(fn=_cmd_diff)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
