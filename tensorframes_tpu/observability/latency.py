"""Dispatch-latency histograms and derived quantiles (p50/p95/p99).

The serving direction (ROADMAP #1) needs per-dispatch latency quantiles
before admission control can exist; the bench trajectory needs them so
a latency regression is machine-checkable (``observability diff``).
Two pre-registered histogram families:

* ``tftpu_verb_latency_seconds{verb=...}`` — wall-clock of one verb
  invocation (all blocks), observed by ``utils/profiling.record``/
  ``span`` at the exact instrumentation points the five verbs already
  hit. ``map_blocks.dispatch`` is the sharded async-dispatch span
  (device-resident outputs return before the TPU finishes) — kept as
  its own series for honesty, same as ``profiling.report``.
* ``tftpu_dispatch_latency_seconds{entry=block|vmap}`` — wall-clock of
  one executor dispatch (one block through one executable), observed in
  ``ops/executor.CompiledProgram._run``. This is the per-request cost a
  serving layer will quote.

Buckets are latency-flavored (10µs … 30s) — finer at the bottom than
``metrics.DEFAULT_BUCKETS`` because a warm CPU dispatch is tens of µs
and p50 must resolve there. Quantiles are derived from bucket counts by
:meth:`metrics.Histogram.quantile` (linear interpolation within the
bucket — the standard Prometheus ``histogram_quantile`` estimate).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .metrics import REGISTRY, Histogram, histogram

__all__ = [
    "LATENCY_BUCKETS",
    "VERBS",
    "observe_verb",
    "verb_histogram",
    "dispatch_histogram",
    "series_key",
    "quantile_summary",
    "summary_lines",
]

#: Latency-flavored bucket ladder: 10µs through 30s.
LATENCY_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

#: The span names that count as verb dispatches (profiling hook filter).
VERBS: Tuple[str, ...] = (
    "map_blocks",
    "map_blocks.dispatch",
    "map_rows",
    "reduce_rows",
    "reduce_blocks",
    "aggregate",
)

_VERB_HISTS: Dict[str, Histogram] = {
    v: histogram(
        "tftpu_verb_latency_seconds",
        "Wall-clock of one verb invocation, by verb "
        "(map_blocks.dispatch = sharded async dispatch only)",
        labels={"verb": v},
        buckets=LATENCY_BUCKETS,
    )
    for v in VERBS
}

_DISPATCH_HISTS: Dict[str, Histogram] = {
    entry: histogram(
        "tftpu_dispatch_latency_seconds",
        "Wall-clock of one executor dispatch (one block through one "
        "executable), by entry kind",
        labels={"entry": entry},
        buckets=LATENCY_BUCKETS,
    )
    for entry in ("block", "vmap")
}


def observe_verb(name: str, seconds: float) -> None:
    """Record one verb invocation's wall-clock — called by
    ``utils/profiling`` for every span/record whose name is a verb;
    non-verb span names are ignored (one dict lookup)."""
    h = _VERB_HISTS.get(name)
    if h is not None:
        h.observe(seconds)


def verb_histogram(verb: str) -> Optional[Histogram]:
    return _VERB_HISTS.get(verb)


def dispatch_histogram(entry: str) -> Histogram:
    return _DISPATCH_HISTS[entry]


def series_key(labels: Dict[str, str]) -> str:
    """Canonical ``family:label`` key for one latency series — e.g.
    ``verb:map_blocks`` / ``dispatch:block``. The ONE naming used by
    bench's ``# latency |`` rows, snapshot latency dicts, and therefore
    the ``latency.<series>.<q>`` metric names ``diff`` compares; any
    new latency family must flow through here or old and new artifacts
    stop sharing metric names."""
    fam = "verb" if "verb" in labels else "dispatch"
    label = "/".join(v for _, v in sorted(labels.items())) or "-"
    return f"{fam}:{label}"


def quantile_summary(
    registry=None, quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99)
) -> List[dict]:
    """Per-series latency quantiles for every latency-family histogram
    with observations: ``[{"name", "labels", "count", "mean", "p50",
    ...}, ...]`` — the structured form bench snapshots embed and the
    ``report`` CLI prints."""
    reg = registry if registry is not None else REGISTRY
    out = []
    for m in reg.collect():
        if not isinstance(m, Histogram):
            continue
        if not m.name.endswith("_latency_seconds"):
            continue
        if m.count == 0:
            continue
        row = {
            "name": m.name,
            "labels": dict(m.labels),
            "count": m.count,
            "mean": m.sum / m.count,
        }
        for q in quantiles:
            row[f"p{int(q * 100)}"] = m.quantile(q)
        out.append(row)
    return sorted(
        out, key=lambda r: (r["name"], sorted(r["labels"].items()))
    )


def summary_lines(registry=None) -> List[str]:
    """Compact per-verb quantile lines — what bench.py prints as
    ``# latency |`` rows next to ``# obs |`` / ``# mfu |``."""
    lines = []
    for row in quantile_summary(registry):
        lines.append(
            f"{series_key(row['labels'])} count={row['count']} "
            f"p50={row['p50']:.6f}s p95={row['p95']:.6f}s "
            f"p99={row['p99']:.6f}s mean={row['mean']:.6f}s"
        )
    return lines
