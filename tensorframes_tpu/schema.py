"""Per-column tensor metadata and frame schemas.

Capability parity with the reference's metadata layer:

* ``ColumnInfo`` ≙ ``SparkTFColInfo`` + ``ColumnInformation``
  (reference: Shape.scala:120-123, ColumnInformation.scala:8-139): each
  column carries a scalar dtype and a *block shape* whose leading dim is the
  row count (usually Unknown) and whose tail is the per-cell shape.
* ``Schema`` ≙ the DataFrame ``StructType`` + ``DataFrameInfo``
  (reference: DataFrameInfo.scala:7-39): ordered named columns with a
  pretty ``explain`` rendering used by ``print_schema``
  (reference: DebugRowOps.scala:535-552, core.py:355-364).

Where the reference smuggles this through Spark ``StructField`` metadata
under keys like ``org.spartf.shape`` (MetadataConstants.scala:19,27), the
TPU-native frame owns its schema outright — there is no foreign engine to
annotate.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional

from . import dtypes as dt
from .shape import Shape


@dataclasses.dataclass(frozen=True)
class ColumnInfo:
    """Metadata for one column: name, scalar dtype, block shape.

    ``block_shape`` includes the leading row-count dim (Unknown unless the
    frame has been analyzed with a pinned count); ``cell_shape`` is its tail.
    Host-only columns (string/binary) always have scalar cells
    (≙ datatypes.scala:577-581).
    """

    name: str
    dtype: dt.ScalarType
    block_shape: Shape

    def __post_init__(self):
        if self.block_shape.rank < 1:
            raise ValueError(
                f"Column {self.name!r}: block shape must have a leading row "
                f"dim, got {self.block_shape}"
            )
        if not self.dtype.device and self.block_shape.rank != 1:
            raise ValueError(
                f"Column {self.name!r}: host-only type {self.dtype.name} "
                f"supports scalar cells only (got cell shape "
                f"{self.block_shape.tail})"
            )

    @property
    def cell_shape(self) -> Shape:
        return self.block_shape.tail

    @property
    def is_device(self) -> bool:
        return self.dtype.device

    def with_block_shape(self, shape: Shape) -> "ColumnInfo":
        return ColumnInfo(self.name, self.dtype, shape)

    def with_name(self, name: str) -> "ColumnInfo":
        return ColumnInfo(name, self.dtype, self.block_shape)

    def merge(self, other: "ColumnInfo") -> "ColumnInfo":
        """Merge metadata from two blocks of the same column (analyze scan);
        disagreeing dims become Unknown (≙ ExperimentalOperations.scala:168-178)."""
        if other.name != self.name:
            raise ValueError(f"Cannot merge columns {self.name!r} and {other.name!r}")
        if other.dtype is not self.dtype:
            raise dt.UnsupportedTypeError(
                f"Column {self.name!r}: conflicting dtypes {self.dtype.name} "
                f"vs {other.dtype.name} (no implicit casting)"
            )
        merged = self.block_shape.merge(other.block_shape)
        if merged is None:
            raise ValueError(
                f"Column {self.name!r}: rank mismatch between blocks: "
                f"{self.block_shape} vs {other.block_shape}"
            )
        return ColumnInfo(self.name, self.dtype, merged)

    def pretty(self) -> str:
        """Render like the reference's explain line: ``name: type[?,2]``
        (cf. README.md:108-109 `` |-- y: array (nullable = false) double[?,2]``)."""
        return f"{self.name}: {self.dtype.name}{self.block_shape}"


class Schema:
    """An ordered collection of ColumnInfo, keyed by name."""

    __slots__ = ("_cols", "_by_name")

    def __init__(self, cols: Iterable[ColumnInfo]):
        cols = list(cols)
        by_name: Dict[str, ColumnInfo] = {}
        for c in cols:
            if c.name in by_name:
                raise ValueError(f"Duplicate column name {c.name!r} in schema")
            by_name[c.name] = c
        self._cols: List[ColumnInfo] = cols
        self._by_name = by_name

    # -- container protocol -------------------------------------------------
    def __iter__(self):
        return iter(self._cols)

    def __len__(self) -> int:
        return len(self._cols)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __getitem__(self, name: str) -> ColumnInfo:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(
                f"Column {name!r} not found. Available columns: {self.names}"
            ) from None

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self._cols == other._cols

    def __repr__(self) -> str:
        return f"Schema({', '.join(c.pretty() for c in self._cols)})"

    # -- accessors ----------------------------------------------------------
    @property
    def names(self) -> List[str]:
        return [c.name for c in self._cols]

    @property
    def columns(self) -> List[ColumnInfo]:
        return list(self._cols)

    @property
    def device_columns(self) -> List[ColumnInfo]:
        return [c for c in self._cols if c.is_device]

    @property
    def host_columns(self) -> List[ColumnInfo]:
        return [c for c in self._cols if not c.is_device]

    def get(self, name: str) -> Optional[ColumnInfo]:
        return self._by_name.get(name)

    # -- transforms ---------------------------------------------------------
    def select(self, names: Iterable[str]) -> "Schema":
        return Schema([self[n] for n in names])

    def replace(self, info: ColumnInfo) -> "Schema":
        return Schema([info if c.name == info.name else c for c in self._cols])

    def append(self, cols: Iterable[ColumnInfo]) -> "Schema":
        return Schema(self._cols + list(cols))

    def merge(self, other: "Schema") -> "Schema":
        """Column-wise metadata merge of two block schemas (same columns)."""
        if self.names != other.names:
            raise ValueError(
                f"Schema mismatch between blocks: {self.names} vs {other.names}"
            )
        return Schema([a.merge(b) for a, b in zip(self._cols, other._cols)])

    # -- rendering ----------------------------------------------------------
    def explain(self) -> str:
        """Tree rendering ≙ the reference's ``explain``/``print_schema``
        output (DebugRowOps.scala:535-552)."""
        lines = ["root"]
        for c in self._cols:
            nullable = "false"
            kind = "array" if c.cell_shape.rank > 0 else c.dtype.name
            lines.append(
                f" |-- {c.name}: {kind} (nullable = {nullable}) "
                f"{c.dtype.name}{c.block_shape}"
            )
        return "\n".join(lines)
