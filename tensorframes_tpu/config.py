"""Runtime configuration for tensorframes_tpu.

The reference has no runtime config system (SURVEY.md §5-config); its only
knobs are per-call ``ShapeDescription`` hints. The TPU build adds a small,
explicit config object because compilation behavior (padding buckets, x64,
default mesh axis names) genuinely needs global knobs on XLA.

All values can be overridden via environment variables (``TFTPU_*``) or
programmatically via :func:`configure`.
"""

from __future__ import annotations

import dataclasses
import os


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return default if v is None else int(v)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return default if v in (None, "") else float(v)


@dataclasses.dataclass
class Config:
    # Enable float64/int64 end-to-end (the reference's Double/Long columns).
    enable_x64: bool = _env_bool("TFTPU_ENABLE_X64", True)
    # map_rows lead-dim bucketing: pad the vmapped row count up to
    # min_bucket * 2**k (k <= max_bucket_doublings) so jit caches stay
    # O(log n) across varying block sizes; padded rows are sliced off
    # (XLA wants static shapes; SURVEY.md §7 hard-part 1). Only row-
    # independent semantics pad — map_blocks programs see the true block.
    min_bucket: int = _env_int("TFTPU_MIN_BUCKET", 8)
    max_bucket_doublings: int = _env_int("TFTPU_MAX_BUCKET_DOUBLINGS", 30)
    # Default number of blocks when partitioning un-blocked input.
    default_num_blocks: int = _env_int("TFTPU_DEFAULT_NUM_BLOCKS", 4)
    # Mesh axis names used by sharded execution.
    batch_axis: str = os.environ.get("TFTPU_BATCH_AXIS", "dp")
    # aggregate(): rows buffered before compaction in the streaming keyed
    # aggregator (≙ TensorFlowUDAF bufferSize=10, DebugRowOps.scala:580).
    aggregate_buffer_size: int = _env_int("TFTPU_AGG_BUFFER", 10)
    # Per-verb timing metrics collection (upgrade over the reference's
    # log4j-only observability, SURVEY.md §5-tracing).
    collect_metrics: bool = _env_bool("TFTPU_METRICS", True)
    # map_blocks keeps this many extra blocks in flight so transfer and
    # compute overlap (0 = fully synchronous per block).
    map_pipeline_depth: int = _env_int("TFTPU_MAP_PIPELINE_DEPTH", 2)
    # map_blocks host-frame path: stage up to this many blocks' feeds in
    # HBM from a background thread (io.prefetch_to_device) so the
    # host→device transfer of block k+1 overlaps block k's compute —
    # the answer to the reference's admitted convert bottleneck
    # (TFDataOps.scala:32-33) on transfer-taxed links (0 = off).
    map_prefetch_depth: int = _env_int("TFTPU_MAP_PREFETCH_DEPTH", 2)
    # Donate freshly-transferred input buffers to the XLA executable so
    # output HBM reuses input HBM (halves peak footprint for big
    # blocks). Only applies where provably safe: host-sourced feeds on
    # backends that implement donation (not XLA:CPU); device-resident
    # frame columns are never donated.
    donate_inputs: bool = _env_bool("TFTPU_DONATE_INPUTS", True)
    # Per-chip peak FLOP/s for MFU accounting in profiling.report()
    # (0 = unknown; bench.py sets it from the detected device kind).
    peak_flops: float = float(os.environ.get("TFTPU_PEAK_FLOPS", 0) or 0)
    # Persistent executable cache directory: first TPU compiles of
    # the big model programs take 20-40s; with a cache dir set, later
    # processes deserialize the executable instead of recompiling
    # (empty = disabled). Two layers share the knob: jax's builtin
    # HLO-keyed cache (wired at import) writes the root, and the AOT
    # executable store (tensorframes_tpu/compilecache — consulted
    # BEFORE lowering, so a hit skips HLO generation and XLA entirely)
    # lives under <dir>/aot.
    compilation_cache_dir: str = os.environ.get("TFTPU_COMPILE_CACHE", "")
    # Byte bound of the AOT executable store (<cache dir>/aot): least-
    # recently-used entries are evicted past it. 0 disables eviction.
    compile_cache_max_bytes: int = _env_int(
        "TFTPU_COMPILE_CACHE_MAX_MB", 2048
    ) * (1 << 20)
    # Lift closure-captured program constants (frozen model weights) out
    # of the HLO and pass them as runtime arguments. Without this, XLA
    # constant-folds through embedded weights — un-doing int8 weight
    # quantization (measured round 3: folded back to f32, zero byte
    # saving) and bloating every per-shape compile with literal copies
    # of the weights.
    hoist_constants: bool = _env_bool("TFTPU_HOIST_CONSTS", True)
    # Multi-process relational verbs (sort_values / join): frames whose
    # replicated side would exceed this byte budget PER PROCESS switch
    # from the replicating plan (allgather sort / broadcast join) to the
    # hash/range-partitioned exchange (ops/exchange.py), which holds
    # only O(global/P) rows per process (VERDICT r4 #2/#7; ≙ Catalyst's
    # hash-partitioned exchange, DebugRowOps.scala:583).
    relational_broadcast_bytes: int = _env_int(
        "TFTPU_RELATIONAL_BROADCAST_MB", 64
    ) * (1 << 20)
    # Kill-switch for the exchange path (debugging): with it off, an
    # over-budget replicated plan raises an actionable error instead of
    # silently OOMing every process at once.
    relational_exchange: bool = _env_bool("TFTPU_RELATIONAL_EXCHANGE", True)
    # Route quantized 2-D matmuls through the pallas int8 kernel
    # (in-kernel dequant: weights stream HBM→VMEM as int8
    # unconditionally, ops/quantize.matmul_pallas_int8). OFF until a
    # real-TPU window shows it beating the XLA structural fusion —
    # dev/tpu_smoke.py prints the adjudicating comparison.
    pallas_int8_matmul: bool = _env_bool("TFTPU_PALLAS_INT8_MM", False)
    # Master switch for the straggler pallas kernels (tensorframes_tpu/
    # kernels: paged int8-KV decode attention, fused segment reduce,
    # ragged gather). TFTPU_PALLAS=0 removes them from every cost-model
    # decision — the CI smoke proves the XLA/host lowerings alone keep
    # every suite green. Distinct from the runtime kill-switch
    # (ops/segment.disable_pallas), which trips on a Mosaic failure.
    pallas_kernels: bool = _env_bool("TFTPU_PALLAS", True)
    # Force-select the straggler kernels even on CPU backends (the
    # pallas interpreter runs them — slow, but the full wiring from
    # cost model to kernel executes). Tests and the in-bench
    # bit-identity gates use this; never enable it for throughput.
    pallas_force: bool = _env_bool("TFTPU_PALLAS_FORCE", False)
    # Lazy verb-chain fusion (tensorframes_tpu/plan): chained lazy maps
    # record a logical plan instead of nesting compute thunks, and each
    # maximal fusable run lowers to ONE composed XLA program dispatched
    # once per block — per-stage jit dispatch, device<->host transfers
    # and intermediate materialization disappear. TFTPU_FUSION=0 is the
    # escape hatch back to per-stage execution (bit-identical results;
    # the fused path exists purely for speed).
    plan_fusion: bool = _env_bool("TFTPU_FUSION", True)
    # Adaptive query optimizer (tensorframes_tpu/plan: aggregate
    # pushdown below joins, multi-join reordering, and feedback
    # re-optimization from the per-plan stats sidecar under
    # TFTPU_COMPILE_CACHE). TFTPU_REOPT=0 is the escape hatch back to
    # the PR 7 static cost model: no plan rewrite, no reordering, no
    # stats recording or consultation — bit-identical results either
    # way (the optimizer exists purely for speed; every rewrite is
    # gated on reassoc_safe-style exactness).
    plan_reopt: bool = _env_bool("TFTPU_REOPT", True)
    # Verified UDF lifting (tensorframes_tpu/analysis/lifting +
    # plan/lift): numpy UDFs captured as host callbacks are statically
    # inspected, synthesized into a pure plan-IR Program, and verified
    # bit-exactly on a bounded boundary-value corpus before
    # substitution — a verified lift clears the TFG107 fusion barrier
    # so map→UDF→aggregate chains compile to one dispatch. Anything
    # that does not verify stays a counted callback barrier with the
    # decline reason in TFG112. TFTPU_LIFT=0 replays the callback path
    # for every UDF — the bit-identity oracle (results are identical
    # either way by construction; the lift exists purely for speed).
    udf_lifting: bool = _env_bool("TFTPU_LIFT", True)
    # Out-of-core data plane (tensorframes_tpu/blockstore): resident-
    # bytes budget of a BlockStore — blocks past it spill to disk
    # least-recently-used, and the streaming partitioner's peak RSS is
    # bounded by (pipeline depth x chunk bytes + this budget) instead
    # of the frame size. Also the TFG111 threshold: a forced
    # to_host/to_numpy materialization estimated past this budget is
    # flagged by lint_plan with the streaming alternative named.
    block_budget_bytes: int = _env_int("TFTPU_BLOCK_BUDGET_MB", 512) * (1 << 20)
    # Default spill directory for block stores (empty = a private temp
    # dir per store, deleted with it). Point at fast local SSD in
    # production; the shuffle's per-rank spill files use the shared
    # rendezvous dir (TFTPU_SHUFFLE_DIR / TFTPU_FLEET_DIR) instead —
    # those must be visible to every rank, this need not be.
    blockstore_dir: str = os.environ.get("TFTPU_BLOCKSTORE_DIR", "")
    # Hung-dispatch watchdog (resilience/fleet.py): a dispatch — or a
    # fleet rendezvous barrier — that exceeds this wall-clock deadline
    # aborts with HungDispatchError plus a flight-recorder postmortem
    # naming the unresponsive ranks, instead of blocking forever inside
    # a collective whose peer died. 0 disables (the default: deadline
    # mode synchronizes dispatch results, trading async pipelining for
    # boundedness, so it is opt-in). Enforced in ops/executor.py and
    # parallel/distributed.py.
    dispatch_deadline_s: float = _env_float("TFTPU_DISPATCH_DEADLINE_S", 0.0)
    # Fleet heartbeat cadence: every process enrolled in a rendezvous
    # dir (TFTPU_FLEET_DIR; supervise() arms it for its children)
    # publishes a beat this often ...
    heartbeat_interval_s: float = _env_float("TFTPU_HEARTBEAT_INTERVAL_S", 0.25)
    # ... and a rank whose newest beat is older than this is declared
    # dead (stragglers are flagged at half the timeout). Must comfortably
    # exceed the longest host-side stall a healthy rank can hit (GC,
    # checkpoint fsync, XLA compile on the driving thread).
    heartbeat_timeout_s: float = _env_float("TFTPU_HEARTBEAT_TIMEOUT_S", 5.0)
    # Demote f64/i64 device columns to f32/i32 at the device boundary:
    # False = never (reference-parity precision, f64 emulated on TPU),
    # True = on TPU backends only, "always" = every backend (testing /
    # CPU measurement). Accounted for in explain(detailed=True).
    demote_x64_on_tpu: object = (
        "always"
        if os.environ.get("TFTPU_DEMOTE_X64", "").lower() == "always"
        else _env_bool("TFTPU_DEMOTE_X64", False)
    )


_config = Config()


def get_config() -> Config:
    return _config


def configure(**kwargs) -> Config:
    """Update global config fields by keyword; returns the live config."""
    for k, v in kwargs.items():
        if not hasattr(_config, k):
            raise AttributeError(f"No such config field: {k!r}")
        setattr(_config, k, v)
    return _config
