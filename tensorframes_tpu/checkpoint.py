"""Checkpoint / resume with verified integrity.

The reference has none (SURVEY.md §5: "Checkpoint / resume: none" — it is
stateless by construction, freezing variables to constants client-side,
core.py:42-56). This framework adds training (optimizer state, sharded
params), so checkpointing becomes first-class, the TPU-native way:

* **orbax backend** (default when importable): async-capable, handles
  sharded ``jax.Array`` pytrees natively — the standard JAX ecosystem
  checkpoint format.
* **npz backend** (fallback, zero extra deps): pytree flattened by
  keypath into one compressed ``.npz`` plus a JSON manifest; atomic via
  write-to-temp + ``os.replace``. Sharded arrays are gathered to host on
  save and restored replicated (callers re-``device_put`` with their
  shardings).

Both sit behind one ``Checkpointer`` API: numbered steps under a root
directory, ``latest_step``, ``save``, ``restore(like=...)``.

Durability & integrity (the resilience subsystem's checkpoint leg):

* ``save`` fsyncs every payload file and the temp directory **before**
  the atomic ``os.replace``, then fsyncs the root — power loss can
  publish the old step or the new step, never a torn one.
* The npz manifest records a CRC32 + byte size **per array**; ``restore``
  verifies them and, when the newest step is truncated or corrupted,
  logs the integrity failure and falls back to the previous intact step
  automatically (explicit ``step=`` requests fail loudly instead).
* ``verify()`` is the audit mode: integrity-check any/all steps without
  materializing state.
* Orphaned ``step_*.tmp*`` directories left by a crashed save are
  garbage-collected on the next ``Checkpointer`` init.
* An optional :class:`~tensorframes_tpu.resilience.RetryPolicy` absorbs
  transient IO faults around save/restore; the ``checkpoint.save`` /
  ``checkpoint.restore`` fault-injection sites live inside the retry
  scope so drills exercise the real path.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import uuid
import zlib
from typing import Any, Dict, List, Optional

import time

import jax
import numpy as np

from .observability import events as _events
from .observability import flight as _flight
from .observability.metrics import counter as _counter
from .observability.metrics import histogram as _histogram
from .resilience.faults import fault_point
from .resilience.retry import RetryError, RetryPolicy, retry_call
from .utils import get_logger
from .utils.npz import decode_array, encode_array

logger = get_logger(__name__)

# Checkpoint-leg telemetry (registered at import). Durations cover the
# full save/restore including retries; bytes count the payload actually
# written/read; CRC failures count per-restore/verify detections — the
# number that turns "restore fell back" from a log line into a graph.
_SAVE_SECONDS = _histogram(
    "tftpu_checkpoint_save_seconds", "Checkpointer.save wall-clock"
)
_RESTORE_SECONDS = _histogram(
    "tftpu_checkpoint_restore_seconds",
    "Checkpointer restore wall-clock (per step dir attempted)",
)
_SAVE_BYTES = _counter(
    "tftpu_checkpoint_save_bytes_total",
    "Bytes published to checkpoint step directories",
)
_RESTORE_BYTES = _counter(
    "tftpu_checkpoint_restore_bytes_total",
    "Raw array bytes read back from checkpoint payloads",
)
_CRC_FAILURES = _counter(
    "tftpu_checkpoint_crc_failures_total",
    "Steps whose CRC/size verification found corruption",
)

_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_\d+\.tmp(\d+)")

# temp dirs with a save currently in flight IN THIS PROCESS — lets the
# init-time GC distinguish "our live save on another thread" from "a
# corpse left by a previous same-pid incarnation" (pid 1 in a restarted
# container is the same pid every time)
_live_tmps: set = set()  # lint: guarded (set add/discard are GIL-atomic; the GC reader tolerates a stale view — worst case it spares one dead tmp until the next init)


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint step failed integrity verification (truncated payload,
    CRC mismatch, unreadable manifest, …)."""


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step}")


def _fsync_path(path: str) -> None:
    """fsync a file or directory, best-effort (directories are not
    fsync-able on every platform/filesystem; durability degrades to the
    OS default there rather than failing the save)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    finally:
        os.close(fd)


def _fsync_tree(path: str) -> None:
    """fsync every file under ``path``, then the directories bottom-up,
    so the subsequent ``os.replace`` publishes fully-durable contents."""
    for dirpath, _dirnames, filenames in os.walk(path, topdown=False):
        for name in filenames:
            _fsync_path(os.path.join(dirpath, name))
        _fsync_path(dirpath)


class Checkpointer:
    """Numbered-step checkpoint store for parameter/optimizer pytrees.

    >>> ckpt = Checkpointer("/tmp/run1")
    >>> ckpt.save(100, {"params": params, "opt": opt_state})
    >>> state = ckpt.restore(like={"params": params0, "opt": opt0})
    """

    def __init__(
        self,
        root: str,
        backend: Optional[str] = None,
        keep: int = 0,
        retry: Optional[RetryPolicy] = None,
    ):
        """``backend``: 'orbax' | 'npz' | None (auto: orbax if importable).
        ``keep``: retain only the newest N step dirs (0 = keep all).
        ``retry``: optional RetryPolicy absorbing transient IO faults
        around save/restore (non-retryable errors propagate untouched)."""
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = keep
        self.retry = retry
        if backend is None:
            try:
                import orbax.checkpoint  # noqa: F401

                backend = "orbax"
            except ImportError:  # pragma: no cover
                backend = "npz"
        if backend not in ("orbax", "npz"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.backend = backend
        self._heal_crashed_swaps()
        self._gc_orphaned_tmps()

    # -- step bookkeeping ---------------------------------------------------

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.root):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_intact_step(self) -> Optional[int]:
        """Newest step whose integrity audit does not FAIL (unverifiable
        orbax/legacy steps count as intact, ``ok=None``) — an audit-side
        prediction of where ``restore_latest`` will land, without
        materializing state. NOTE: callers that need the landed step to
        stay consistent with the restored state should use
        ``restore_latest`` itself (one read, no prediction gap) — that is
        what ``run_resumable``/``train_on_frame`` do; this helper is for
        monitoring/drills."""
        for s in reversed(self.all_steps()):
            if self.verify(s)[s]["ok"] is not False:
                return s
        return None

    def _gc(self) -> None:
        if self.keep <= 0:
            return
        steps = self.all_steps()
        for s in steps[: -self.keep]:
            shutil.rmtree(_step_dir(self.root, s), ignore_errors=True)

    def _heal_crashed_swaps(self) -> None:
        """Recover ``step_N.old`` aside-dirs left by a save killed inside
        its publish window: if ``step_N`` never appeared, the aside copy
        IS the step — rename it back; otherwise it is superseded refuse."""
        for name in os.listdir(self.root):
            if not name.endswith(".old"):
                continue
            base = name[: -len(".old")]
            if not _STEP_RE.match(base):
                continue
            old = os.path.join(self.root, name)
            final = os.path.join(self.root, base)
            if os.path.isdir(final):
                shutil.rmtree(old, ignore_errors=True)
            else:
                try:
                    os.rename(old, final)
                except OSError:
                    # a sibling process relaunching on the same shared
                    # root healed (or re-saved) first; losing that race
                    # must not kill our init
                    if not os.path.isdir(final):
                        raise
                    shutil.rmtree(old, ignore_errors=True)
                    continue
                logger.warning(
                    "Checkpointer: healed %s from a crashed publish", base
                )

    def _gc_orphaned_tmps(self) -> None:
        """Remove ``step_*.tmp<pid>_*`` directories left behind by a save
        that crashed before its atomic rename. Temp names embed the
        writer's pid, and only corpses whose writer is **dead** are
        collected — a replacement process restarting on a shared root
        must not delete the old process's still-in-flight emergency save
        (pid reuse makes this best-effort, which only delays GC)."""
        for name in os.listdir(self.root):
            m = _TMP_RE.match(name)
            if not m:
                continue
            full = os.path.join(self.root, name)
            pid = int(m.group(1))
            if full in _live_tmps:
                continue  # this process's save, in flight on another thread
            if pid != os.getpid():
                # another process's temp: a corpse only if the writer died
                # (a pid-1 container restart reuses the pid, which is why
                # same-pid temps are judged by the _live_tmps registry
                # above, not by liveness)
                try:
                    os.kill(pid, 0)
                    continue  # writer still alive: not a corpse
                except ProcessLookupError:
                    pass
                except OSError:  # pragma: no cover - EPERM: can't tell
                    continue
            shutil.rmtree(full, ignore_errors=True)
            logger.warning(
                "Checkpointer: removed orphaned temp %s (crashed save)",
                name,
            )

    def _io(self, fn, describe: str):
        """Run a save/restore closure under the configured retry policy
        (retry=None → retry_call degrades to a plain call)."""
        return retry_call(fn, policy=self.retry, describe=describe)

    # -- save / restore -----------------------------------------------------

    def save(self, step: int, state: Any) -> str:
        """Write ``state`` (a pytree of arrays) as step ``step``. Atomic
        AND durable: payloads are fsynced before the rename publishes the
        step dir, so a crash at any instant leaves either the previous
        intact step or the new one — never a torn directory."""
        final = _step_dir(self.root, step)

        def write() -> None:
            fault_point("checkpoint.save")
            # attempt-unique temp name: a watchdog-abandoned attempt may
            # still be writing its tree when the retry starts — sharing
            # one name would let the two attempts rmtree each other
            tmp = final + f".tmp{os.getpid()}_{uuid.uuid4().hex[:8]}"
            shutil.rmtree(tmp, ignore_errors=True)
            _live_tmps.add(tmp)
            try:
                if self.backend == "orbax":
                    self._save_orbax(tmp, state)
                else:
                    self._save_npz(tmp, state)
                _fsync_tree(tmp)
                # publish via rename-aside (same pattern as io.save_frame):
                # an existing same-step dir moves ASIDE, the new one swaps
                # in, only then is the old deleted — rmtree-then-rename
                # would leave NO published step if a SIGKILL landed between
                # the two calls (exactly the emergency-save-then-grace-kill
                # shape). A crash inside the window leaves the aside copy,
                # healed by the next Checkpointer init or same-step save.
                old = final + ".old"
                if os.path.isdir(old) and not os.path.isdir(final):
                    os.rename(old, final)  # heal a previous crashed swap
                shutil.rmtree(old, ignore_errors=True)
                if os.path.isdir(final):
                    os.rename(final, old)
                os.replace(tmp, final)
                shutil.rmtree(old, ignore_errors=True)
                _fsync_path(self.root)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
                _live_tmps.discard(tmp)

        t0 = time.perf_counter()
        self._io(write, f"checkpoint.save(step={step})")
        dt = time.perf_counter() - t0
        _SAVE_SECONDS.observe(dt)
        try:
            nbytes = sum(
                os.path.getsize(os.path.join(dirpath, f))
                for dirpath, _dirs, files in os.walk(final)
                for f in files
            )
            _SAVE_BYTES.inc(nbytes)
        except OSError:  # pragma: no cover - racing GC on the step dir
            nbytes = -1
        if _events.TRACER.enabled:
            _events.TRACER.emit_complete(
                "checkpoint.save", t0, dt,
                args={"step": step, "bytes": nbytes}, cat="checkpoint",
            )
        _flight.record(
            "checkpoint.save", step=step, seconds=round(dt, 6),
            bytes=nbytes,
        )
        self._gc()
        return final

    def restore(
        self,
        step: Optional[int] = None,
        like: Any = None,
        verify: bool = True,
    ) -> Any:
        """Read step ``step`` (default: latest **intact**). ``like`` is a
        template pytree (same treedef; array leaves) — required for npz
        round-trips of non-dict pytrees and for orbax sharding restoration.

        With ``step=None`` a corrupted/truncated newest step is logged
        and skipped, falling back to the previous step that verifies —
        the recovery contract a preempted trainer relies on. An explicit
        ``step=`` raises :class:`CheckpointCorruptionError` instead (the
        caller asked for that exact state). ``verify=False`` skips CRC
        verification (trusted-fast path; structural errors still raise).
        """
        if step is not None:
            return self._restore_step(step, like, verify)
        return self.restore_latest(like=like, verify=verify)[1]

    def restore_latest(
        self, like: Any = None, verify: bool = True
    ) -> tuple:
        """Restore the newest **intact** step, falling back past
        corrupted ones. Returns ``(step, state)`` — callers that replay
        data deterministically (``run_resumable``) need to know which
        step actually came back, which ``latest_step()`` cannot promise
        once corruption enters the picture."""
        steps = self.all_steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        last_err: Optional[BaseException] = None
        for s in reversed(steps):
            try:
                return s, self._restore_step(s, like, verify)
            except CheckpointCorruptionError as e:
                logger.error(
                    "checkpoint step %d failed integrity verification (%s); "
                    "falling back to the previous step", s, e,
                )
                last_err = e
        raise CheckpointCorruptionError(
            f"no intact checkpoint under {self.root} "
            f"({len(steps)} step(s) all failed verification)"
        ) from last_err

    def _restore_step(self, step: int, like: Any, verify: bool) -> Any:
        path = _step_dir(self.root, step)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path}")

        def read() -> Any:
            fault_point("checkpoint.restore")
            # dispatch on the on-disk format, not the configured backend,
            # so a checkpoint written where orbax was (un)available
            # restores anywhere
            if os.path.exists(os.path.join(path, "manifest.json")):
                return self._restore_npz(path, like, verify)
            try:
                return self._restore_orbax(path, like)
            except FileNotFoundError as e:
                # missing orbax files count as corruption so the
                # step=None fallback can engage. ValueError/KeyError stay
                # caller errors (a mismatched `like` template raises them
                # for EVERY step — sweeping past N intact checkpoints and
                # reporting 'no intact checkpoint' would send the
                # operator hunting disk corruption that isn't there);
                # other OSErrors stay transient/retryable
                raise CheckpointCorruptionError(
                    f"orbax restore of {path} failed: {e}"
                ) from e

        # observe in finally: the interesting restores (corruption
        # fallback sweeps, retry exhaustion) are the ones that raise,
        # and they must still land in the histogram and on the timeline
        t0 = time.perf_counter()
        ok = False
        try:
            out = self._io(read, f"checkpoint.restore(step={step})")
            ok = True
            return out
        finally:
            dt = time.perf_counter() - t0
            _RESTORE_SECONDS.observe(dt)
            if _events.TRACER.enabled:
                _events.TRACER.emit_complete(
                    "checkpoint.restore", t0, dt,
                    args={"step": step, "ok": ok}, cat="checkpoint",
                )
            _flight.record(
                "checkpoint.restore", step=step, seconds=round(dt, 6),
                ok=ok,
            )

    # -- integrity audit ----------------------------------------------------

    def verify(self, step: Optional[int] = None) -> Dict[int, Dict[str, Any]]:
        """Audit checkpoint integrity without materializing state.

        Returns ``{step: {"format", "ok", "errors"}}`` for the given step
        (or every step). ``ok`` is True/False for npz steps; ``None`` for
        orbax steps (no per-array manifest to check — only structural
        presence is asserted) and legacy npz steps predating the CRC
        manifest.
        """
        steps = [step] if step is not None else self.all_steps()
        report: Dict[int, Dict[str, Any]] = {}
        for s in steps:
            path = _step_dir(self.root, s)
            entry: Dict[str, Any] = {"format": None, "ok": None, "errors": []}
            if not os.path.isdir(path):
                entry["ok"] = False
                entry["errors"].append(f"missing step directory {path}")
            elif os.path.exists(os.path.join(path, "manifest.json")):
                entry["format"] = "npz"
                try:
                    manifest, raws = self._io(
                        lambda p=path: self._read_npz_payload(p),
                        f"checkpoint.verify(step={s})",
                    )
                    legacy = bool(manifest) and isinstance(manifest[0], str)
                    if legacy:
                        entry["errors"].append(
                            "legacy manifest (no CRC records)"
                        )
                    else:
                        errs = self._crc_errors(manifest, raws)
                        if errs:
                            _CRC_FAILURES.inc()
                        entry["errors"].extend(errs)
                        entry["ok"] = not errs
                except CheckpointCorruptionError as e:
                    entry["ok"] = False
                    entry["errors"].append(str(e))
                except (OSError, RetryError) as e:
                    # transient read failure (possibly after retry
                    # exhaustion): unknown, not corrupt — the audit must
                    # return its report, never raise
                    entry["errors"].append(f"transient read error: {e}")
            else:
                entry["format"] = "orbax"
                if not os.path.exists(os.path.join(path, "state")):
                    entry["ok"] = False
                    entry["errors"].append("missing orbax state directory")
                else:
                    entry["errors"].append(
                        "orbax step: no per-array CRC manifest to verify"
                    )
            report[s] = entry
        return report

    # -- orbax backend ------------------------------------------------------

    def _save_orbax(self, path: str, state: Any) -> None:
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, "state"), state)

    def _restore_orbax(self, path: str, like: Any) -> Any:
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            if like is not None:
                target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, like)
                return ckptr.restore(os.path.join(path, "state"), target)
            return ckptr.restore(os.path.join(path, "state"))

    # -- npz backend --------------------------------------------------------

    def _save_npz(self, path: str, state: Any) -> None:
        # leaves are stored as raw bytes + (dtype, shape) in the manifest
        # (utils/npz.py): numpy's npz loader cannot reconstruct ml_dtypes.
        # each entry additionally records the byte size and CRC32 of the
        # raw payload so restore can prove the arrays it read are the
        # arrays that were written.
        os.makedirs(path, exist_ok=True)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        arrays = {}
        manifest = []
        for i, (keypath, leaf) in enumerate(flat):
            arrays[f"a{i}"], entry = encode_array(leaf)
            entry["key"] = jax.tree_util.keystr(keypath)
            entry["nbytes"] = int(arrays[f"a{i}"].nbytes)
            # the encoded view is contiguous uint8: crc straight off the
            # buffer, no tobytes() copy of a possibly-multi-GB leaf
            entry["crc32"] = zlib.crc32(arrays[f"a{i}"])
            manifest.append(entry)
        np.savez_compressed(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())

    def _read_npz_payload(self, path: str):
        """Read (manifest, {name: raw array}). Structural failures
        (missing file, unparseable json/zip) become
        :class:`CheckpointCorruptionError`; transient OSErrors (EIO, NFS
        blips) propagate untouched so a configured retry policy can
        classify and retry them instead of silently falling back to an
        older step."""
        # _CRC_FAILURES counts each npz-payload integrity DETECTION (here
        # and in _restore_npz's CRC/missing-array checks) — not every
        # CheckpointCorruptionError construction, which would over-count
        # restore_latest's no-intact-checkpoint summary raise and count
        # orbax structural wrappers as "CRC" failures
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError) as e:
            _CRC_FAILURES.inc()
            raise CheckpointCorruptionError(
                f"unreadable manifest.json in {path}: {e}"
            ) from e
        try:
            # materialize all arrays inside the context: a truncated zip
            # member surfaces here, not lazily after we returned
            with np.load(os.path.join(path, "arrays.npz")) as data:
                raws = {k: data[k] for k in data.files}
        except FileNotFoundError as e:
            _CRC_FAILURES.inc()
            raise CheckpointCorruptionError(
                f"missing arrays.npz in {path}: {e}"
            ) from e
        except OSError:
            raise  # transient IO: retryable, not corruption
        except Exception as e:
            _CRC_FAILURES.inc()
            raise CheckpointCorruptionError(
                f"unreadable arrays.npz in {path}: {e}"
            ) from e
        return manifest, raws

    @staticmethod
    def _crc_errors(manifest, raws) -> List[str]:
        """Per-array integrity errors for a modern (dict-entry) manifest.
        Entries written before the CRC format (no 'crc32' key) are
        skipped — old checkpoints stay restorable, just unverified."""
        errors = []
        for i, entry in enumerate(manifest):
            name = f"a{i}"
            if name not in raws:
                errors.append(f"array {name} ({entry.get('key')}) missing")
                continue
            raw = raws[name]
            if "nbytes" in entry and int(raw.nbytes) != int(entry["nbytes"]):
                errors.append(
                    f"array {name} ({entry.get('key')}): size "
                    f"{raw.nbytes} != manifest {entry['nbytes']} (truncated?)"
                )
                continue
            if "crc32" in entry and zlib.crc32(
                np.ascontiguousarray(raw)
            ) != entry["crc32"]:
                errors.append(
                    f"array {name} ({entry.get('key')}): CRC32 mismatch"
                )
        return errors

    def _restore_npz(self, path: str, like: Any, verify: bool = True) -> Any:
        manifest, raws = self._read_npz_payload(path)
        _RESTORE_BYTES.inc(sum(int(r.nbytes) for r in raws.values()))
        legacy = bool(manifest) and isinstance(manifest[0], str)
        if not legacy and verify:
            errors = self._crc_errors(manifest, raws)
            if errors:
                _CRC_FAILURES.inc()
                raise CheckpointCorruptionError(
                    f"{path}: " + "; ".join(errors)
                )
        leaves = []
        for i, entry in enumerate(manifest):
            try:
                raw = raws[f"a{i}"]
            except KeyError:
                _CRC_FAILURES.inc()
                raise CheckpointCorruptionError(
                    f"{path}: array a{i} missing from arrays.npz"
                ) from None
            if legacy:
                # pre-byte-format checkpoints stored arrays directly
                # (native dtypes only); keep them restorable
                leaves.append(raw)
            else:
                leaves.append(decode_array(raw, entry))
        keys = manifest if legacy else [e["key"] for e in manifest]
        if like is None:
            # reconstruct as a flat {keystr: array} dict
            return dict(zip(keys, leaves))
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        if len(flat) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(leaves)} leaves, template has {len(flat)}"
            )
        for (keypath, _), key in zip(flat, keys):
            if jax.tree_util.keystr(keypath) != key:
                raise ValueError(
                    f"checkpoint leaf {key!r} does not match "
                    f"template leaf {jax.tree_util.keystr(keypath)!r}"
                )
        return jax.tree_util.tree_unflatten(treedef, leaves)
