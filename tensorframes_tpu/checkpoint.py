"""Checkpoint / resume.

The reference has none (SURVEY.md §5: "Checkpoint / resume: none" — it is
stateless by construction, freezing variables to constants client-side,
core.py:42-56). This framework adds training (optimizer state, sharded
params), so checkpointing becomes first-class, the TPU-native way:

* **orbax backend** (default when importable): async-capable, handles
  sharded ``jax.Array`` pytrees natively — the standard JAX ecosystem
  checkpoint format.
* **npz backend** (fallback, zero extra deps): pytree flattened by
  keypath into one compressed ``.npz`` plus a JSON manifest; atomic via
  write-to-temp + ``os.replace``. Sharded arrays are gathered to host on
  save and restored replicated (callers re-``device_put`` with their
  shardings).

Both sit behind one ``Checkpointer`` API: numbered steps under a root
directory, ``latest_step``, ``save``, ``restore(like=...)``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, List, Optional

import jax
import numpy as np

from .utils import get_logger
from .utils.npz import decode_array, encode_array

logger = get_logger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step}")


class Checkpointer:
    """Numbered-step checkpoint store for parameter/optimizer pytrees.

    >>> ckpt = Checkpointer("/tmp/run1")
    >>> ckpt.save(100, {"params": params, "opt": opt_state})
    >>> state = ckpt.restore(like={"params": params0, "opt": opt0})
    """

    def __init__(self, root: str, backend: Optional[str] = None, keep: int = 0):
        """``backend``: 'orbax' | 'npz' | None (auto: orbax if importable).
        ``keep``: retain only the newest N step dirs (0 = keep all)."""
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = keep
        if backend is None:
            try:
                import orbax.checkpoint  # noqa: F401

                backend = "orbax"
            except ImportError:  # pragma: no cover
                backend = "npz"
        if backend not in ("orbax", "npz"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.backend = backend

    # -- step bookkeeping ---------------------------------------------------

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.root):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _gc(self) -> None:
        if self.keep <= 0:
            return
        steps = self.all_steps()
        for s in steps[: -self.keep]:
            shutil.rmtree(_step_dir(self.root, s), ignore_errors=True)

    # -- save / restore -----------------------------------------------------

    def save(self, step: int, state: Any) -> str:
        """Write ``state`` (a pytree of arrays) as step ``step``. Atomic:
        the step dir only appears once fully written."""
        final = _step_dir(self.root, step)
        tmp = final + f".tmp{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            if self.backend == "orbax":
                self._save_orbax(tmp, state)
            else:
                self._save_npz(tmp, state)
            # the previous step dir is removed only after the new one is
            # fully written, keeping the crash window to the rename itself
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        self._gc()
        return final

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Read step ``step`` (default: latest). ``like`` is a template
        pytree (same treedef; array leaves) — required for npz round-trips
        of non-dict pytrees and for orbax sharding restoration."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.root}")
        path = _step_dir(self.root, step)
        if not os.path.isdir(path):
            raise FileNotFoundError(f"no checkpoint at {path}")
        # dispatch on the on-disk format, not the configured backend, so a
        # checkpoint written where orbax was (un)available restores anywhere
        if os.path.exists(os.path.join(path, "manifest.json")):
            return self._restore_npz(path, like)
        return self._restore_orbax(path, like)

    # -- orbax backend ------------------------------------------------------

    def _save_orbax(self, path: str, state: Any) -> None:
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, "state"), state)

    def _restore_orbax(self, path: str, like: Any) -> Any:
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            if like is not None:
                target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, like)
                return ckptr.restore(os.path.join(path, "state"), target)
            return ckptr.restore(os.path.join(path, "state"))

    # -- npz backend --------------------------------------------------------

    def _save_npz(self, path: str, state: Any) -> None:
        # leaves are stored as raw bytes + (dtype, shape) in the manifest
        # (utils/npz.py): numpy's npz loader cannot reconstruct ml_dtypes.
        os.makedirs(path, exist_ok=True)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        arrays = {}
        manifest = []
        for i, (keypath, leaf) in enumerate(flat):
            arrays[f"a{i}"], entry = encode_array(leaf)
            entry["key"] = jax.tree_util.keystr(keypath)
            manifest.append(entry)
        np.savez_compressed(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f)

    def _restore_npz(self, path: str, like: Any) -> Any:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        legacy = bool(manifest) and isinstance(manifest[0], str)
        with np.load(os.path.join(path, "arrays.npz")) as data:
            leaves = []
            for i, entry in enumerate(manifest):
                raw = data[f"a{i}"]
                if legacy:
                    # pre-byte-format checkpoints stored arrays directly
                    # (native dtypes only); keep them restorable
                    leaves.append(raw)
                else:
                    leaves.append(decode_array(raw, entry))
        keys = manifest if legacy else [e["key"] for e in manifest]
        if like is None:
            # reconstruct as a flat {keystr: array} dict
            return dict(zip(keys, leaves))
        flat, treedef = jax.tree_util.tree_flatten_with_path(like)
        if len(flat) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(leaves)} leaves, template has {len(flat)}"
            )
        for (keypath, _), key in zip(flat, keys):
            if jax.tree_util.keystr(keypath) != key:
                raise ValueError(
                    f"checkpoint leaf {key!r} does not match "
                    f"template leaf {jax.tree_util.keystr(keypath)!r}"
                )
        return jax.tree_util.tree_unflatten(treedef, leaves)
