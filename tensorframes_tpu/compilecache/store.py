"""Size-bounded on-disk store of serialized XLA executables.

One entry per fingerprint (:mod:`.fingerprint`): a self-describing
file ``<fp>.xc`` holding a JSON header plus the payload from jax's AOT
``serialize_executable``. The executor consults the store on a
jit-cache miss **before lowering**: a hit deserializes the executable
(milliseconds) instead of paying trace-to-HLO + XLA compile (seconds
to minutes on TPU). The store is **off by default** — it activates
only when ``TFTPU_COMPILE_CACHE`` / ``configure(compilation_cache_dir=
...)`` names a directory — and every store problem degrades to a
normal compile: a cache failure must never fail a dispatch.

Durability & concurrency (same discipline as checkpoint.py):

* entries publish via write-temp → fsync → atomic ``os.replace`` —
  readers never observe a torn entry, and two processes racing to
  write the same fingerprint both succeed (last replace wins; the
  content is identical by construction);
* the payload carries a CRC32; corrupt/truncated entries are detected
  on load, counted, quarantined (unlinked), and fall back to a fresh
  compile;
* eviction is LRU by bytes (mtime, refreshed on hit) against
  ``config.compile_cache_max_bytes``.

Treedefs are not pickled: the header stores a JSON *skeleton* of the
call's in/out pytrees (dict/list/tuple of leaf markers), rebuilt into
real ``PyTreeDef``\\ s at load time — version-safe where pickling jax
internals is not. Entries whose trees cannot round-trip the skeleton
codec are never stored.

A ``manifest.jsonl`` beside the entries records the feed shapes of
every store miss, so :func:`tensorframes_tpu.compilecache.warmup` can
replay yesterday's traffic shapes ahead of today's.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..observability.metrics import counter as _counter
from ..observability.metrics import gauge as _gauge
from ..observability.metrics import histogram as _histogram
from ..utils import get_logger
from .fingerprint import FORMAT_VERSION

logger = get_logger(__name__)

__all__ = ["CompileCacheStore", "active_store", "store_for"]

_MAGIC = b"TFXC"
_ENTRY_SUFFIX = ".xc"

# Registered at import (TFL003): a process that never enables the
# store still expositions the whole family at 0.
_HITS = _counter(
    "tftpu_compilecache_hits_total",
    "Executables served from the persistent AOT store instead of compiled",
)
_MISSES = _counter(
    "tftpu_compilecache_misses_total",
    "Store lookups that found no entry (a fresh compile follows)",
)
_LOAD_SECONDS = _histogram(
    "tftpu_compilecache_load_seconds",
    "Wall-clock to read + CRC-check + deserialize one stored executable",
)
_BYTES_WRITTEN = _counter(
    "tftpu_compilecache_bytes_total",
    "Bytes of serialized executables written to the persistent store",
)
_STORE_BYTES = _gauge(
    "tftpu_compilecache_store_bytes",
    "Current total size of the persistent store directory's entries",
)
_EVICTIONS = _counter(
    "tftpu_compilecache_evictions_total",
    "Entries removed by LRU eviction against the byte bound",
)
_FALLBACKS = {
    reason: _counter(
        "tftpu_compilecache_fallback_total",
        "Store operations abandoned in favor of a normal compile, by reason",
        labels={"reason": reason},
    )
    for reason in (
        "corrupt", "deserialize", "store_error", "tree_unsupported",
        "unavailable", "unfingerprintable",
    )
}


def note_unfingerprintable() -> None:
    """Count a dispatch that skipped the store because its program
    could not be fingerprinted — e.g. a plain-form baked const whose
    values cannot be hashed (a non-addressable multi-process global
    capture). The dispatch still AOT-compiles in-process; it just never
    publishes or hits, which on a fleet means every rank of every
    restart recompiles — this counter is how that shows up instead of
    staying a debug-level log line."""
    _FALLBACKS["unfingerprintable"].inc()

_STORE_LOCK = threading.Lock()
_STORES: Dict[Tuple[str, int], Optional["CompileCacheStore"]] = {}


# ---------------------------------------------------------------------------
# treedef ⇄ JSON skeleton codec
# ---------------------------------------------------------------------------

def _encode_skeleton(obj) -> object:
    """Pytree container skeleton → JSON-able form. Leaves become the
    marker 0; dict (str keys) / list / tuple / namedtuple / None
    containers are supported — anything else raises and the entry is
    not stored. Namedtuples (optax optimizer states — the generic
    ``aot_jit`` entry serializes whole train steps) record their
    importable class path and are reconstructed at load; a class that
    no longer imports degrades to a fresh compile like any other
    defect."""
    if isinstance(obj, dict):
        if not all(isinstance(k, str) for k in obj):
            raise TypeError("non-string dict keys in pytree")
        return {"t": "d", "k": sorted(obj),
                "v": [_encode_skeleton(obj[k]) for k in sorted(obj)]}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        cls = type(obj)
        if cls.__module__ in (None, "__main__"):
            raise TypeError(
                f"namedtuple {cls.__name__} is not importable cross-process"
            )
        return {"t": "nt", "c": f"{cls.__module__}:{cls.__qualname__}",
                "v": [_encode_skeleton(x) for x in obj]}
    if isinstance(obj, tuple):
        return {"t": "t", "v": [_encode_skeleton(x) for x in obj]}
    if isinstance(obj, list):
        return {"t": "l", "v": [_encode_skeleton(x) for x in obj]}
    if obj is None:
        return {"t": "n"}
    return 0  # leaf


def _resolve_namedtuple(path: str):
    import importlib

    mod_name, _, qual = path.partition(":")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    if not (isinstance(obj, type) and issubclass(obj, tuple)
            and hasattr(obj, "_fields")):
        raise TypeError(f"{path} is not a namedtuple class")
    return obj


def _decode_skeleton(enc) -> object:
    if enc == 0:
        return 0
    t = enc["t"]
    if t == "d":
        return {k: _decode_skeleton(v) for k, v in zip(enc["k"], enc["v"])}
    if t == "nt":
        cls = _resolve_namedtuple(enc["c"])
        return cls(*(_decode_skeleton(v) for v in enc["v"]))
    if t == "t":
        return tuple(_decode_skeleton(v) for v in enc["v"])
    if t == "l":
        return [_decode_skeleton(v) for v in enc["v"]]
    if t == "n":
        return None
    raise ValueError(f"unknown skeleton tag {t!r}")


def _treedef_to_skeleton(treedef) -> object:
    import jax

    skeleton = jax.tree_util.tree_unflatten(
        treedef, [0] * treedef.num_leaves
    )
    return _encode_skeleton(skeleton)


def _skeleton_to_treedef(enc):
    import jax

    return jax.tree_util.tree_structure(_decode_skeleton(enc))


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class CompileCacheStore:
    """One directory of ``<fingerprint>.xc`` entries + manifest.jsonl."""

    def __init__(self, root: str, max_bytes: int = 0):
        self.root = root
        self.max_bytes = int(max_bytes)
        self.manifest_path = os.path.join(root, "manifest.jsonl")
        self._manifest_seen: set = set()
        self._lock = threading.Lock()
        # fingerprints whose SHARED entry failed to deserialize on this
        # rank (multi-process only): the recompile publishes under a
        # rank-scoped key instead, and later lookups prefer it — the
        # "per-rank disambiguation only where XLA partitions differ"
        # escape hatch. Fleets whose ranks load each other's entries
        # (the SPMD norm: one global module) never populate this.
        self._rank_incompatible: set = set()
        os.makedirs(root, exist_ok=True)

    # -- paths --------------------------------------------------------------

    def _path(self, fp: str) -> str:
        if not fp or any(c in fp for c in "/\\."):
            raise ValueError(f"bad fingerprint {fp!r}")
        return os.path.join(self.root, fp + _ENTRY_SUFFIX)

    def _entries(self) -> List[Tuple[str, float, int]]:
        """[(path, mtime, size)] of current entries, oldest first."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if not name.endswith(_ENTRY_SUFFIX):
                continue
            p = os.path.join(self.root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue  # raced with an eviction elsewhere
            out.append((p, st.st_mtime, st.st_size))
        out.sort(key=lambda e: e[1])
        return out

    # -- read ---------------------------------------------------------------

    def _read_entry(self, path: str) -> Tuple[dict, bytes]:
        """Parse + CRC-check one entry file; raises on any defect."""
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:4] != _MAGIC:
            raise ValueError("bad magic")
        (version,) = struct.unpack("<I", blob[4:8])
        if version != FORMAT_VERSION:
            raise ValueError(f"format version {version}")
        (hlen,) = struct.unpack("<Q", blob[8:16])
        header = json.loads(blob[16:16 + hlen].decode("utf-8"))
        payload = blob[16 + hlen:]
        if len(payload) != header["payload_bytes"]:
            raise ValueError("truncated payload")
        if zlib.crc32(payload) != header["payload_crc32"]:
            raise ValueError("payload CRC mismatch")
        return header, payload

    @staticmethod
    def _rank_fp(fp: str, rank: int) -> str:
        return f"{fp}_r{int(rank)}"

    def get(self, fp: str, rank: Optional[int] = None):
        """Load and deserialize the executable for ``fp``. Returns the
        loaded callable or None (miss / any defect — defects are
        counted, quarantined, and never raised).

        ``rank`` (multi-process fleets pass their process index) arms
        per-rank disambiguation: a rank-scoped entry ``<fp>_r<rank>``
        is preferred when present, and a SHARED entry that fails to
        deserialize on this rank is left in place for the peers that
        CAN load it (quarantining would thrash the fleet) — this rank
        remembers the incompatibility and republishes rank-scoped."""
        if rank is not None:
            scoped = self._load_one(self._rank_fp(fp, rank), shared=False,
                                    count_miss=False)
            if scoped is not None:
                return scoped
        loaded = self._load_one(fp, shared=rank is not None)
        if loaded is None and rank is not None and os.path.exists(
            self._path(fp)
        ):
            with self._lock:
                self._rank_incompatible.add(fp)
        return loaded

    def _load_one(self, fp: str, shared: bool, count_miss: bool = True):
        path = self._path(fp)
        if not os.path.exists(path):
            if count_miss:
                _MISSES.inc()
            return None
        t0 = time.perf_counter()
        try:
            header, payload = self._read_entry(path)
        except Exception as e:
            logger.warning("compile cache entry %s unreadable (%s); "
                           "quarantining, falling back to compile",
                           os.path.basename(path), e)
            _FALLBACKS["corrupt"].inc()
            self._quarantine(path)
            return None
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            loaded = deserialize_and_load(
                payload,
                _skeleton_to_treedef(header["in_skel"]),
                _skeleton_to_treedef(header["out_skel"]),
            )
        except Exception as e:
            # structurally sound but not loadable here (runtime drift,
            # incompatible executable): fall back. Single-process drops
            # the entry so a fresh compile re-publishes a loadable one;
            # a fleet rank leaves the shared entry for its peers and
            # goes rank-scoped instead (see get()).
            logger.warning("compile cache entry %s failed to "
                           "deserialize (%s); falling back to compile",
                           os.path.basename(path), e)
            _FALLBACKS["deserialize"].inc()
            if not shared:
                self._quarantine(path)
            return None
        _HITS.inc()
        _LOAD_SECONDS.observe(time.perf_counter() - t0)
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return loaded

    def _quarantine(self, path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- write --------------------------------------------------------------

    def put(self, fp: str, compiled, meta: Optional[dict] = None,
            rank: Optional[int] = None) -> bool:
        """Serialize + publish one executable. Best-effort: returns
        False (and counts the reason) instead of raising. With ``rank``
        given and ``fp`` previously observed rank-incompatible (a peer's
        shared entry would not deserialize here — see :meth:`get`), the
        entry publishes under the rank-scoped key so this rank's restart
        hits without disturbing the peers' shared entry."""
        if rank is not None:
            with self._lock:
                scoped = fp in self._rank_incompatible
            if scoped:
                fp = self._rank_fp(fp, rank)
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            try:
                in_skel = _treedef_to_skeleton(in_tree)
                out_skel = _treedef_to_skeleton(out_tree)
                if (_skeleton_to_treedef(in_skel) != in_tree
                        or _skeleton_to_treedef(out_skel) != out_tree):
                    raise TypeError("treedef does not round-trip")
            except Exception as e:
                logger.debug("not storing %s: %s", fp, e)
                _FALLBACKS["tree_unsupported"].inc()
                return False
            header = dict(meta or {})
            header.update({
                "fingerprint": fp,
                "created": round(time.time(), 3),
                "payload_bytes": len(payload),
                "payload_crc32": zlib.crc32(payload),
                "in_skel": in_skel,
                "out_skel": out_skel,
            })
            hbytes = json.dumps(header, sort_keys=True).encode("utf-8")
            blob = (_MAGIC + struct.pack("<I", FORMAT_VERSION)
                    + struct.pack("<Q", len(hbytes)) + hbytes + payload)
            path = self._path(fp)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            from ..checkpoint import _fsync_path

            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic publish; racing writers both win
            _fsync_path(self.root)
            _BYTES_WRITTEN.inc(len(blob))
            self._evict()
            return True
        except Exception as e:
            logger.warning("compile cache store of %s failed (%s); "
                           "continuing uncached", fp, e)
            _FALLBACKS["store_error"].inc()
            return False

    def _evict(self) -> None:
        """LRU-evict entries until total bytes fit the bound. The
        newest entry survives even when alone over the bound — evicting
        what was just published would thrash."""
        entries = self._entries()
        total = sum(s for _, _, s in entries)
        _STORE_BYTES.set(total)
        if self.max_bytes <= 0:
            return
        while total > self.max_bytes and len(entries) > 1:
            path, _, size = entries.pop(0)
            try:
                os.unlink(path)
            except OSError:
                if os.path.exists(path):
                    continue  # undeletable but still present: skip it
                # a racing process already evicted it — its bytes are
                # gone from disk either way, so the accounting must
                # drop them or we over-evict live entries
                total -= size
                continue
            total -= size
            _EVICTIONS.inc()
            logger.info("compile cache evicted %s (%d bytes; store over "
                        "%d-byte bound)", os.path.basename(path), size,
                        self.max_bytes)
        _STORE_BYTES.set(total)

    # -- manifest -----------------------------------------------------------

    def record_miss(self, kind: str,
                    inputs: Sequence[Tuple[str, Tuple[int, ...], str]],
                    donate: bool, sharded: bool = False) -> None:
        """Append one feed-shape record for warmup replay (deduped per
        process; best-effort — manifest problems never surface).
        ``sharded`` marks feeds carrying non-trivial placements: warmup
        replay skips those rows unless it can reconstruct the mesh (the
        shapes alone under-specify the executable's layout)."""
        row = {
            "kind": kind,
            "inputs": sorted([n, list(s), d] for (n, s, d) in inputs),
            "donate": bool(donate),
        }
        if sharded:
            row["sharded"] = True
        key = json.dumps(row, sort_keys=True)
        with self._lock:
            if key in self._manifest_seen:
                return
            self._manifest_seen.add(key)
        try:
            with open(self.manifest_path, "a") as f:
                f.write(key + "\n")
        except OSError as e:
            logger.debug("manifest append failed: %s", e)

    def read_manifest(self) -> List[dict]:
        rows: List[dict] = []
        try:
            with open(self.manifest_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail line from a crashed writer
        except OSError:
            pass
        return rows

    # -- ops surface (CLI) --------------------------------------------------

    def stats(self) -> dict:
        entries = []
        for path, mtime, size in self._entries():
            row = {
                "fingerprint": os.path.basename(path)[:-len(_ENTRY_SUFFIX)],
                "bytes": size,
                "mtime": round(mtime, 3),
            }
            try:
                header, _ = self._read_entry(path)
                for k in ("kind", "form", "backend", "device_kind", "jax",
                          "donate", "inputs", "created"):
                    if k in header:
                        row[k] = header[k]
            except Exception:
                row["unreadable"] = True
            entries.append(row)
        return {
            "root": self.root,
            "max_bytes": self.max_bytes,
            "entries": len(entries),
            "bytes": sum(e["bytes"] for e in entries),
            "manifest_rows": len(self.read_manifest()),
            "entry_list": entries,
        }

    def verify(self, delete_bad: bool = False) -> dict:
        """CRC + header check of every entry (no deserialization — that
        is backend-specific); optionally removes defective entries."""
        good, bad = [], []
        for path, _, _ in self._entries():
            name = os.path.basename(path)
            try:
                self._read_entry(path)
                good.append(name)
            except Exception as e:
                bad.append({"entry": name, "error": str(e)})
                if delete_bad:
                    self._quarantine(path)
        return {"ok": not bad, "good": len(good), "bad": bad,
                "deleted": len(bad) if delete_bad else 0}

    def prune(self, max_bytes: Optional[int] = None,
              clear: bool = False) -> dict:
        """Evict to ``max_bytes`` (default: the configured bound), or
        drop everything with ``clear=True``."""
        removed = 0
        if clear:
            for path, _, _ in self._entries():
                try:
                    os.unlink(path)
                    removed += 1
                except OSError:
                    pass
            try:
                os.unlink(self.manifest_path)
            except OSError:
                pass
        else:
            bound = self.max_bytes if max_bytes is None else int(max_bytes)
            entries = self._entries()
            total = sum(s for _, _, s in entries)
            while entries and total > bound:
                path, _, size = entries.pop(0)
                try:
                    os.unlink(path)
                except OSError:
                    if os.path.exists(path):
                        continue  # undeletable but present: skip it
                    total -= size  # already gone: bytes left the disk
                    continue
                total -= size
                removed += 1
        left = self._entries()
        _STORE_BYTES.set(sum(s for _, _, s in left))
        return {"removed": removed, "entries": len(left),
                "bytes": sum(s for _, _, s in left)}


def store_for(root: str, max_bytes: Optional[int] = None
              ) -> Optional["CompileCacheStore"]:
    """Store instance for an explicit directory (CLI surface); None
    when the directory cannot be created."""
    from ..config import get_config

    mb = get_config().compile_cache_max_bytes if max_bytes is None \
        else int(max_bytes)
    key = (os.path.abspath(root), mb)
    with _STORE_LOCK:
        if key not in _STORES:
            try:
                _STORES[key] = CompileCacheStore(key[0], mb)
            except OSError as e:
                logger.warning("compile cache unavailable at %s: %s",
                               root, e)
                _FALLBACKS["unavailable"].inc()
                _STORES[key] = None
        return _STORES[key]


def active_store() -> Optional["CompileCacheStore"]:
    """The config-selected store (``<compilation_cache_dir>/aot``), or
    None when the cache is disabled — the default, in which case every
    dispatch behaves exactly as if this subsystem did not exist."""
    from ..config import get_config

    root = get_config().compilation_cache_dir
    if not root:
        return None
    return store_for(os.path.join(root, "aot"))
