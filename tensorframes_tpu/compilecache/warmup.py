"""Warmup: precompile expected feed-shape buckets ahead of traffic.

``tfs.warmup(frame_or_schema, programs_or_verbs, ...)`` builds (or
disk-loads) the executor's per-shape executables for the shapes real
dispatches will use, **without executing anything** — warmed keys are
marked dispatched, so the first real dispatch at that shape is a
jit-cache hit with zero compile. Combined with a persistent store
(``TFTPU_COMPILE_CACHE``), a serving process can reach first-request
latency equal to steady-state latency.

Shape selection mirrors the dispatch paths exactly:

* **block mode** (``map_blocks``): the frame partitioner yields at most
  two block row counts (``n//k`` and ``n//k + 1``) — both are warmed;
  a materialized frame's actual distinct block sizes win over the
  estimate.
* **rows mode** (``map_rows``): lead dims are rounded through the same
  power-of-two bucket ladder the executor pads into
  (:func:`~tensorframes_tpu.ops.executor.bucket_rows`).
* an explicit ``rows=[...]`` overrides both; a recorded **shape
  manifest** (``manifest=``, appended by the executor on every store
  miss) replays yesterday's real traffic shapes.

Pass :class:`~tensorframes_tpu.program.Program` objects (from
``tfs.compile_program``) rather than bare functions when you want the
warmed in-process executables to be reused by later verb calls — a
bare function normalizes to a fresh Program per call, so its warmth
lives only in the persistent store (still skipping XLA, not the trace).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import get_logger

logger = get_logger(__name__)

__all__ = [
    "WarmupReport", "warmup", "warm_program", "partitioner_row_counts",
    "serving_row_buckets", "decode_slot_buckets", "decode_warmup_grid",
]


@dataclasses.dataclass
class WarmupReport:
    """What a warmup pass did: one row per (program, kind, shape)."""

    entries: List[dict] = dataclasses.field(default_factory=list)

    def add(self, subject: str, kind: str, rows: Optional[int],
            status: str, detail: str = "") -> None:
        self.entries.append({
            "subject": subject, "kind": kind, "rows": rows,
            "status": status, "detail": detail,
        })

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.entries:
            out[e["status"]] = out.get(e["status"], 0) + 1
        return out

    @property
    def compiled(self) -> int:
        return self.counts().get("compiled", 0)

    @property
    def disk_hits(self) -> int:
        return self.counts().get("disk", 0)

    def pretty(self) -> str:
        c = self.counts()
        head = "warmup: " + ", ".join(
            f"{k}={v}" for k, v in sorted(c.items())
        ) if c else "warmup: nothing to do"
        lines = [head]
        for e in self.entries:
            rows = "?" if e["rows"] is None else e["rows"]
            extra = f" ({e['detail']})" if e["detail"] else ""
            lines.append(
                f"  {e['subject']} [{e['kind']} rows={rows}]: "
                f"{e['status']}{extra}"
            )
        return "\n".join(lines)

    def __repr__(self) -> str:  # pragma: no cover - convenience
        return self.pretty()


def _as_program_list(fetches, schema, block: bool, feed_dict):
    """Normalize the ``programs_or_verbs`` argument: a single fetches
    item or a sequence of them, each becoming one Program. A list of
    DSL nodes is ONE multi-output program (verb semantics)."""
    from ..dsl.node import Node
    from ..ops.verbs import _apply_feed_dict, _normalize_program
    from ..program import Program

    if isinstance(fetches, (list, tuple)) and fetches and not all(
        isinstance(f, Node) for f in fetches
    ):
        items = list(fetches)
    else:
        items = [fetches]
    out = []
    for item in items:
        if isinstance(item, Program) and item.outputs:
            program = item
        else:
            if schema is None:
                raise ValueError(
                    "warmup() needs a frame or schema to normalize "
                    "non-Program fetches (pass tfs.compile_program "
                    "results to warm without one)"
                )
            program, _ = _normalize_program(
                item, schema, block=block, feed_dict=feed_dict
            )
        program = _apply_feed_dict(program, feed_dict)
        out.append(program)
    return out


def partitioner_row_counts(total: int, num_blocks: int) -> List[int]:
    """The at-most-two block sizes the frame partitioner yields for
    ``total`` rows in ``num_blocks`` blocks (``n//k`` and ``n//k+1``) —
    the serving-side estimate when only expected traffic volume is
    known: ``warmup(schema, prog, rows=partitioner_row_counts(n, k))``."""
    num_blocks = max(1, int(num_blocks))
    base = total // num_blocks
    sizes = {base, base + 1} if total % num_blocks else {base}
    return sorted(s for s in sizes if s > 0) or [total]


def serving_row_buckets(max_rows: int) -> List[int]:
    """The power-of-two lead-dim buckets a serving batcher's flushes
    can land on: every ladder bucket up to ``bucket_rows(max_rows)``
    (the serving layer caps any single flush at
    ``ServingConfig.max_batch_rows`` = ``max_rows``). ONE policy,
    stated once: the batcher pads flushes through
    :func:`~tensorframes_tpu.ops.executor.bucket_rows`, and
    ``warm_program(p, rows=serving_row_buckets(m), block=False)``
    precompiles exactly those keys — which is how a warmed server
    sustains zero steady-state compiles under any request-size mix."""
    from ..ops.executor import bucket_rows, bucket_table

    max_rows = int(max_rows)
    if max_rows < 1:
        raise ValueError(f"max_rows must be >= 1, got {max_rows}")
    table = bucket_table()
    if max_rows > table[-1]:
        # beyond the ladder bucket_rows falls back to EXACT counts, so
        # a batcher flushing (table[-1], max_rows] sizes would dispatch
        # never-warmed shapes — the zero-steady-state-compile contract
        # cannot hold; refuse instead of warming a false promise
        raise ValueError(
            f"max_rows={max_rows} exceeds the bucket ladder's top "
            f"({table[-1]}): flush sizes above the ladder dispatch at "
            "exact, unwarmable shapes. Raise TFTPU_MAX_BUCKET_DOUBLINGS"
            "/configure(max_bucket_doublings=) or lower "
            "ServingConfig.max_batch_rows"
        )
    top = bucket_rows(max_rows)
    return [b for b in table if b <= top]


def decode_slot_buckets(max_slots: int) -> List[int]:
    """The slot-count buckets the iterative decode engine's batched
    step can dispatch at — BY CONSTRUCTION the same power-of-two ladder
    as :func:`serving_row_buckets`, because a decode slot count is a
    vmapped lead dim like any flush's row count. ONE bucket policy,
    stated once, shared by three consumers that must never drift:

    * the flush batcher pads coalesced rows through
      ``ops.executor.bucket_rows``;
    * ``Server.start()`` warms ``serving_row_buckets(max_batch_rows)``;
    * the decode engine pads its running slot count through THIS ladder
      and warms every (phase × bucket) pair at start
      (:func:`decode_warmup_grid`).

    The delegation (not a reimplementation) is the drift guard: any
    change to the ladder — ``min_bucket``, ``max_bucket_doublings``,
    the beyond-ladder refusal — applies to rows and slots identically.
    Asserted against ``bucket_rows`` below so a future fork of either
    policy fails loudly here rather than as a steady-state compile."""
    from ..ops.executor import bucket_rows

    buckets = serving_row_buckets(max_slots)
    for n in range(1, int(max_slots) + 1):
        if bucket_rows(n) not in buckets:
            raise AssertionError(
                f"bucket policy drift: bucket_rows({n}) = "
                f"{bucket_rows(n)} is not in the warmed ladder "
                f"{buckets} — serving_row_buckets and bucket_rows no "
                "longer agree; fix the shared ladder, do not fork it"
            )
    return buckets


def decode_warmup_grid(max_slots: int,
                       max_prompt_len: int) -> Dict[str, List[int]]:
    """The slot-count × phase bucket grid a decode engine must warm for
    zero steady-state compiles: one decode-step executable per slot
    bucket, one prefill executable per prompt-length bucket (prompt
    lengths pad through the SAME ladder — a prefill chunk's token dim
    is a vmapped lead dim too). The engine's ``start()`` walks exactly
    this grid; tests assert no dispatch ever lands off it."""
    return {
        "decode": decode_slot_buckets(max_slots),
        "prefill": serving_row_buckets(max_prompt_len),
    }


def _target_row_counts(frame, rows, block: bool) -> List[int]:
    if rows is not None:
        counts = sorted({int(r) for r in rows if int(r) > 0})
        if not counts:
            raise ValueError("warmup rows= must contain positive ints")
        return counts
    if frame is None:
        raise ValueError(
            "warmup() needs rows=[...] when no frame is given"
        )
    if frame.is_materialized:
        from ..frame import _block_num_rows

        return sorted({_block_num_rows(b) for b in frame.blocks()})
    # lazy frame: never force it — a pinned block lead dim in the
    # schema IS the block row count; otherwise give up loudly
    for col in frame.schema.columns:
        d = col.block_shape.dims[0]
        if isinstance(d, int):
            return [int(d)]
    raise ValueError(
        "warmup() cannot infer block sizes from a lazy frame with "
        "unknown row counts; pass rows=[...] (warmup never forces "
        "a pending computation)"
    )


def _abstract_feeds(program, n: int, kind: str):
    """ShapeDtypeStruct feeds at lead dim ``n``, exactly as the
    executor will see them (map_rows buckets the vmapped lead dim;
    dtypes follow the program's input specs, which gather_feeds casts
    feeds to). Returns None when an input has unknown inner dims."""
    import jax
    import jax.numpy as jnp

    from ..shape import Unknown

    feeds = {}
    for spec in program.inputs:
        dims = list(spec.shape.dims)
        if kind == "block":
            dims[0] = n
            cell = dims[1:]
        else:
            cell = dims
            dims = [n] + dims
        if any(d == Unknown for d in cell):
            return None
        # the key must match runtime exactly: run paths jnp.asarray the
        # gathered feeds, which can re-type under the x64 flag
        dtype = jnp.asarray(np.zeros((), dtype=spec.dtype.np_dtype)).dtype
        feeds[spec.name] = jax.ShapeDtypeStruct(
            tuple(int(d) for d in dims), dtype
        )
    return feeds


def _default_donate() -> bool:
    """Match the verbs' choice for host-sourced feeds: donate when the
    config asks for it and the backend implements it."""
    from ..config import get_config
    from ..ops.executor import donation_supported

    return bool(get_config().donate_inputs) and donation_supported()


def warm_program(program, rows: Sequence[int], block: bool = True,
                 donate: Optional[bool] = None,
                 report: Optional[WarmupReport] = None) -> WarmupReport:
    """Warm one analyzed Program at explicit lead-dim row counts (the
    CLI surface; :func:`warmup` is the frame-aware front door)."""
    from ..ops.executor import bucket_rows

    report = report if report is not None else WarmupReport()
    donate = _default_donate() if donate is None else bool(donate)
    kind = "block" if block else "vmap"
    subject = f"Program(inputs={program.input_names})"
    if block:
        targets = sorted({int(r) for r in rows})
    else:
        # map_rows buckets adaptively: exact shapes while the frame
        # presents few sizes (the partitioner's ≤2), power-of-two
        # buckets once shapes proliferate — warm both regimes
        targets = sorted(
            {int(r) for r in rows} | {bucket_rows(int(r)) for r in rows}
        )
    for n in targets:
        feeds = _abstract_feeds(program, n, kind)
        if feeds is None:
            report.add(subject, kind, n, "skipped",
                       "unknown inner dims (ragged cells warm per group "
                       "at dispatch)")
            continue
        status = program.compiled().warm(kind, feeds, donate=donate)
        report.add(subject, kind, n, status)
    return report


def _manifest_row_matches(program, row) -> bool:
    """A manifest row targets this program only when every recorded
    input matches the program's spec by name, dtype, AND known cell
    dims — the manifest is store-wide, and warming program A with
    program B's shapes (they often share names like 'x' or 'images')
    would burn spurious multi-second compiles on junk keys."""
    import jax.numpy as jnp

    from ..shape import Unknown

    inputs = row.get("inputs", [])
    if sorted(n for (n, _, _) in inputs) != sorted(program.input_names):
        return False
    kind = row.get("kind", "block")
    for (name, shape, dtype) in inputs:
        try:
            spec = program.input(name)
        except KeyError:
            return False
        want = jnp.asarray(np.zeros((), dtype=spec.dtype.np_dtype)).dtype
        if str(want) != str(np.dtype(dtype)):
            return False
        # recorded shapes are block-level (post-gather): lead dim is the
        # row count; the tail must fit the spec's cell dims
        cell = list(spec.shape.dims[1:]) if kind == "block" \
            else list(spec.shape.dims)
        if len(shape) != len(cell) + 1:
            return False
        for got, want_d in zip(shape[1:], cell):
            if want_d != Unknown and int(got) != int(want_d):
                return False
    return True


def _warm_from_manifest(programs, manifest_rows, report: WarmupReport,
                        donate: Optional[bool]) -> None:
    import jax

    for program in programs:
        subject = f"Program(inputs={program.input_names})"
        for row in manifest_rows:
            if not _manifest_row_matches(program, row):
                continue
            if row.get("sharded"):
                # record_miss(sharded=True) marks feeds with non-trivial
                # placements: shapes alone under-specify the executable's
                # layout, so replaying would compile (and publish) an
                # UNSHARDED key the real sharded dispatch never hits —
                # warm those via warmup(frame.to_device(mesh), ...)
                report.add(subject, row.get("kind", "block"), None,
                           "skipped", "sharded manifest row (warm via a "
                           "sharded frame instead)")
                continue
            try:
                feeds = {
                    n: jax.ShapeDtypeStruct(
                        tuple(int(d) for d in s), np.dtype(t)
                    )
                    for (n, s, t) in row["inputs"]
                }
            except (TypeError, ValueError):
                continue  # torn or stale manifest row
            d = row.get("donate", False) if donate is None else donate
            status = program.compiled().warm(
                row.get("kind", "block"), feeds,
                donate=bool(d),
            )
            lead = None
            for v in feeds.values():
                lead = int(v.shape[0]) if v.shape else None
                break
            report.add(subject, row.get("kind", "block"), lead, status,
                       "manifest")


def warmup(frame_or_schema, programs_or_verbs, *, rows=None,
           block: bool = True, feed_dict=None, donate: Optional[bool] = None,
           manifest=None) -> WarmupReport:
    """Precompile the executables real traffic will need (ISSUE 5).

    ``frame_or_schema`` — a TensorFrame (block sizes inferred from the
    partitioner contract / the materialized blocks), a Schema (pass
    ``rows=``), or None when every fetch is an analyzed Program.
    ``programs_or_verbs`` — one fetches item or a sequence: Programs,
    plain functions, or DSL nodes (a list of nodes is one program).
    ``rows=[...]`` — explicit lead-dim row counts (map_rows targets are
    rounded through the executor's power-of-two bucket ladder).
    ``manifest=`` — True (the active store's recorded miss manifest) or
    a path: replay previously-observed feed shapes instead of/in
    addition to the partitioner estimate.

    Returns a :class:`WarmupReport`; warm keys make the first real
    dispatch a jit-cache hit with zero compile (and, with a persistent
    store, zero XLA even in a fresh process).
    """
    schema = getattr(frame_or_schema, "schema", frame_or_schema)
    frame = frame_or_schema if hasattr(frame_or_schema, "schema") else None
    programs = _as_program_list(
        programs_or_verbs, schema, block=block, feed_dict=feed_dict
    )
    report = WarmupReport()

    manifest_rows = []
    if manifest:
        if manifest is True:
            from .store import active_store

            store = active_store()
            if store is None:
                raise ValueError(
                    "warmup(manifest=True) needs an active persistent "
                    "store — set TFTPU_COMPILE_CACHE or "
                    "configure(compilation_cache_dir=...), or pass the "
                    "manifest path explicitly"
                )
            manifest_rows = store.read_manifest()
        else:
            import os as _os

            if not _os.path.exists(str(manifest)):
                raise ValueError(
                    f"warmup manifest {manifest!r} does not exist — a "
                    "silently-empty warmup would leave the first "
                    "request paying the full compile"
                )
            from .store import CompileCacheStore

            probe = CompileCacheStore.__new__(CompileCacheStore)
            probe.manifest_path = str(manifest)
            manifest_rows = CompileCacheStore.read_manifest(probe)
        _warm_from_manifest(programs, manifest_rows, report, donate)

    if rows is not None or frame is not None or not manifest:
        counts = _target_row_counts(frame, rows, block)
        for program in programs:
            warm_program(program, counts, block=block, donate=donate,
                         report=report)
    return report
