"""``python -m tensorframes_tpu.compilecache`` — ops surface for the
persistent AOT executable store.

Subcommands (see docs/compilecache.md for the runbook):

* ``stats``  — entry count / bytes / per-entry metadata of a store;
* ``warm``   — precompile serialized Program bundles (``save_program``
  artifacts) at given row counts into the store;
* ``prune``  — LRU-evict to a byte bound, or ``--clear`` everything;
* ``verify`` — CRC + header check every entry, optionally deleting
  defective ones.

The store directory comes from ``--store`` or ``TFTPU_COMPILE_CACHE``
(the same knob the runtime uses; the AOT entries live under
``<dir>/aot``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _resolve_store(args, create: bool = False):
    from ..config import get_config
    from .store import store_for

    root = args.store or get_config().compilation_cache_dir
    if not root:
        print("no store: pass --store DIR or set TFTPU_COMPILE_CACHE",
              file=sys.stderr)
        return None
    aot = os.path.join(root, "aot")
    if not create and not os.path.isdir(aot):
        print(f"no store at {aot} (empty cache is a valid state: "
              "stats would be all zeros)", file=sys.stderr)
        return None
    return store_for(aot)


def _cmd_stats(args) -> int:
    store = _resolve_store(args)
    if store is None:
        return 1
    s = store.stats()
    if args.json:
        print(json.dumps(s, sort_keys=True))
        return 0
    print(f"store: {s['root']}")
    print(f"entries: {s['entries']}  bytes: {s['bytes']:,}  "
          f"bound: {s['max_bytes']:,}  manifest rows: {s['manifest_rows']}")
    for e in s["entry_list"]:
        if e.get("unreadable"):
            print(f"  {e['fingerprint'][:16]}…  {e['bytes']:>10,}B  "
                  "UNREADABLE (run verify)")
            continue
        ins = ",".join(
            f"{n}:{'x'.join(str(d) for d in shp)}:{dt}"
            for (n, shp, dt) in e.get("inputs", [])
        )
        print(f"  {e['fingerprint'][:16]}…  {e['bytes']:>10,}B  "
              f"{e.get('kind', '?'):5} {e.get('form', '?'):7} "
              f"{e.get('backend', '?'):4} {ins}")
    return 0


def _cmd_warm(args) -> int:
    store = _resolve_store(args, create=True)
    if store is None:
        return 1
    # route the runtime at this store for the duration of the warm
    from ..config import configure

    configure(compilation_cache_dir=args.store
              or os.environ.get("TFTPU_COMPILE_CACHE", ""))
    from ..program import load_program
    from .warmup import WarmupReport, warm_program

    rows = [int(r) for r in args.rows.split(",") if r.strip()]
    report = WarmupReport()
    for path in args.bundles:
        program = load_program(path)
        from ..program import analyze_program

        program = analyze_program(program)
        warm_program(program, rows, block=(args.mode == "block"),
                     report=report)
    print(report.pretty())
    return 0 if not report.counts().get("failed") else 1


def _cmd_prune(args) -> int:
    store = _resolve_store(args)
    if store is None:
        return 1
    max_bytes = None if args.max_mb is None else args.max_mb * (1 << 20)
    out = store.prune(max_bytes=max_bytes, clear=args.clear)
    print(json.dumps(out, sort_keys=True))
    return 0


def _cmd_verify(args) -> int:
    store = _resolve_store(args)
    if store is None:
        return 1
    out = store.verify(delete_bad=args.delete_bad)
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        print(f"good: {out['good']}  bad: {len(out['bad'])}  "
              f"deleted: {out['deleted']}")
        for b in out["bad"]:
            print(f"  BAD {b['entry']}: {b['error']}")
    return 0 if out["ok"] else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tensorframes_tpu.compilecache",
        description="Inspect and manage the persistent AOT executable "
                    "store (docs/compilecache.md)",
    )
    p.add_argument("--store", default="",
                   help="cache root (default: $TFTPU_COMPILE_CACHE)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("stats", help="entry count/bytes/metadata")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=_cmd_stats)

    wp = sub.add_parser(
        "warm", help="precompile Program bundles into the store"
    )
    wp.add_argument("bundles", nargs="+",
                    help="save_program() StableHLO bundle paths")
    wp.add_argument("--rows", required=True,
                    help="comma-separated lead-dim row counts, e.g. 64,65")
    wp.add_argument("--mode", choices=("block", "rows"), default="block")
    wp.set_defaults(fn=_cmd_warm)

    pp = sub.add_parser("prune", help="LRU-evict to a byte bound")
    pp.add_argument("--max-mb", type=int, default=None)
    pp.add_argument("--clear", action="store_true",
                    help="drop every entry and the manifest")
    pp.set_defaults(fn=_cmd_prune)

    vp = sub.add_parser("verify", help="CRC-check every entry")
    vp.add_argument("--delete-bad", action="store_true")
    vp.add_argument("--json", action="store_true")
    vp.set_defaults(fn=_cmd_verify)

    args = p.parse_args(argv)
    return args.fn(args)
