"""Stable content fingerprints for compiled-program cache keys.

The persistent executable store (:mod:`.store`) keys entries by a hash
that must survive process restarts, so nothing here may depend on
Python ``hash()`` (randomized per process), object ids, or memory
addresses. The fingerprint covers everything that changes the compiled
artifact:

* the program's **jaxpr** (pretty-printed, with ``0x…`` memory
  addresses scrubbed — a closure that traces identically in two
  processes must key identically);
* the **constants** closed over by the trace: avals always, values
  only in the *plain* (closure-capture) form where XLA bakes them into
  the executable — the hoisted form passes weights as runtime
  arguments, so different weights share one cached executable;
* the **feed-shape bucket**: sorted (name, shape, dtype) of the
  abstract inputs the executable was specialized to;
* the **dtype policy** (x64 flag + demotion mode) and the fetch order;
* the **environment**: backend, device kind, device/process count,
  ``XLA_FLAGS``, jax version, entry kind (block/vmap), donation and
  hoist flags, and the store format version.

``TFG108`` (analysis/rules.py) calls :func:`program_fingerprint` twice
with independent traces: a program whose fingerprint differs across
identical rebuilds (non-deterministically serialized captures) would
miss the persistent store on every process start — a miss storm.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

#: Bumped whenever the entry layout or key composition changes: old
#: entries simply miss (never mis-deserialize).
FORMAT_VERSION = 1

__all__ = [
    "FORMAT_VERSION",
    "fingerprint_from_closed",
    "program_fingerprint",
]

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _scrub(text: str) -> str:
    """Drop process-local memory addresses from jaxpr text (function
    reprs inside callback/custom-primitive params embed them)."""
    return _ADDR_RE.sub("0x", text)


def _const_digest(h, const, include_values: bool,
                  value_policy: str) -> None:
    """Feed one traced constant into the running hash. ``value_policy``
    'host_only' skips device-array values (the lint surface must not
    trigger device→host transfers); 'all' hashes every value (the
    compile path — a transfer is noise next to the XLA compile)."""
    try:
        import jax

        is_device = isinstance(const, jax.Array)
    except Exception:  # pragma: no cover - jax always importable here
        is_device = False
    try:
        if include_values and (value_policy == "all" or not is_device):
            arr = np.asarray(const)
            h.update(str((arr.shape, str(arr.dtype))).encode())
            h.update(arr.tobytes())
        else:
            shape = getattr(const, "shape", None)
            dtype = getattr(const, "dtype", None)
            h.update(str((tuple(shape) if shape is not None else None,
                          str(dtype))).encode())
    except (TypeError, ValueError):
        # non-array capture: repr is the best available identity; if it
        # embeds process-local state, TFG108 is the rule that says so
        h.update(_scrub(repr(const)).encode())


def _env_parts(kind: str, donate: bool, hoisted: bool) -> Dict[str, object]:
    import jax

    from ..config import get_config

    cfg = get_config()
    dev = jax.devices()[0]
    return {
        "format": FORMAT_VERSION,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "n_devices": jax.device_count(),
        "n_processes": jax.process_count(),
        "x64": bool(jax.config.jax_enable_x64),
        "demote_x64": str(cfg.demote_x64_on_tpu),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "kind": kind,
        "donate": bool(donate),
        "form": "hoisted" if hoisted else "plain",
    }


def fingerprint_from_closed(
    closed,
    avals: Iterable[Tuple[str, Tuple[int, ...], str]],
    out_names: Sequence[str],
    *,
    kind: str = "block",
    donate: bool = False,
    hoisted: bool = False,
    value_policy: str = "all",
) -> str:
    """Fingerprint an already-traced program.

    ``closed`` is the ``ClosedJaxpr`` of the (possibly vmapped) entry
    function; ``avals`` the sorted (name, shape, dtype-str) triples of
    the feed the executable is specialized to; ``out_names`` the fetch
    order. Hoisted form excludes const *values* from the key (they are
    runtime arguments of the cached executable).
    """
    h = hashlib.sha256()
    h.update(_scrub(str(closed.jaxpr)).encode())
    h.update(b"|consts:%d|" % len(closed.consts))
    for c in closed.consts:
        _const_digest(h, c, include_values=not hoisted,
                      value_policy=value_policy)
    h.update(json.dumps({
        "avals": [(n, list(s), d) for (n, s, d) in avals],
        "outs": list(out_names),
        "env": _env_parts(kind, donate, hoisted),
    }, sort_keys=True).encode())
    return h.hexdigest()[:40]


def program_fingerprint(
    program,
    probe: int = 8,
    *,
    kind: str = "block",
    donate: bool = False,
    hoisted: bool = False,
    value_policy: str = "host_only",
) -> Optional[str]:
    """Trace ``program`` fresh and fingerprint it (plain form by
    default — const values in the key, exactly what the executor uses
    when constant hoisting is off). Each call re-traces, so two calls
    on one program probe rebuild stability (TFG108). Returns None when
    the program cannot be traced."""
    import jax

    from ..program import _abstract_inputs

    abstract = _abstract_inputs(program.inputs, probe)

    def rebuilt(feeds):
        # a fresh function object per call defeats jax's trace cache
        # (keyed on fn identity + avals): each fingerprint really does
        # re-run the user's capture logic, which is the whole point of
        # the TFG108 stability probe
        return program.fn(feeds)

    try:
        closed = jax.make_jaxpr(rebuilt)(abstract)
    except Exception:
        return None
    avals = sorted(
        (name, tuple(int(d) for d in np.shape(a)), str(a.dtype))
        for name, a in abstract.items()
    )
    outs = list(program.fetch_order or [o.name for o in program.outputs])
    return fingerprint_from_closed(
        closed, avals, outs, kind=kind, donate=donate, hoisted=hoisted,
        value_policy=value_policy,
    )
