"""Stable content fingerprints for compiled-program cache keys.

The persistent executable store (:mod:`.store`) keys entries by a hash
that must survive process restarts, so nothing here may depend on
Python ``hash()`` (randomized per process), object ids, or memory
addresses. The fingerprint covers everything that changes the compiled
artifact:

* the program's **jaxpr** (pretty-printed, with ``0x…`` memory
  addresses scrubbed — a closure that traces identically in two
  processes must key identically);
* the **constants** closed over by the trace: avals always, values
  only in the *plain* (closure-capture) form where XLA bakes them into
  the executable — the hoisted form passes weights as runtime
  arguments, so different weights share one cached executable;
* the **feed-shape bucket**: sorted (name, shape, dtype) of the
  abstract inputs the executable was specialized to;
* the **input shardings**: per-argument sharding descriptors
  (mesh axis names + shape + device assignment + per-dim partition
  spec — :func:`~tensorframes_tpu.parallel.mesh.sharding_descriptor`),
  because an AOT executable is layout-specialized and XLA compiles a
  different collective schedule per layout;
* the **dtype policy** (x64 flag + demotion mode) and the fetch order;
* the **environment**: backend, device kind, device/process count, the
  process-index-independent **fleet topology** (device → process map,
  :func:`~tensorframes_tpu.parallel.distributed.process_topology` —
  every rank of an SPMD fleet computes the same key, so one rank's
  published executable is every rank's hit; resizing the fleet misses
  cleanly), ``XLA_FLAGS``, jax version, entry kind (block/vmap/fn),
  donation and hoist flags, the straggler-kernel selection state
  (:func:`tensorframes_tpu.kernels.fingerprint_token` — pallas
  enabled/kill-switched, force hook, interpreter mode), and the store
  format version.

``TFG108`` (analysis/rules.py) calls :func:`program_fingerprint` twice
with independent traces: a program whose fingerprint differs across
identical rebuilds (non-deterministically serialized captures) would
miss the persistent store on every process start — a miss storm.
:func:`fingerprint_components` exposes the per-component digests so the
rule can *name* the unstable component (including which input's
sharding) instead of reporting an opaque hash mismatch.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

#: Bumped whenever the entry layout or key composition changes: old
#: entries simply miss (never mis-deserialize). v2: sharding/topology
#: axes joined the key (unified sharded/multi-process AOT dispatch).
#: v3: the straggler-kernel selection state joined the env component
#: (ISSUE 12 — a ``disable_pallas()`` flip or a ``TFTPU_PALLAS``
#: change must never serve a stale executable).
#: v4: the verified-lift state joined the env component (ISSUE 18 —
#: a ``TFTPU_LIFT`` flip or a synthesis-rule bump swaps a lifted
#: program for a callback one; the two must never share a key).
FORMAT_VERSION = 4

__all__ = [
    "FORMAT_VERSION",
    "content_digest",
    "fingerprint_components",
    "fingerprint_from_closed",
    "frame_content_digest",
    "part_signature",
    "program_fingerprint",
]

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


# ---------------------------------------------------------------------------
# input-partition content digests (ISSUE 20): the OTHER half of the
# registered-query result-cache key. The plan fingerprint
# (plan/stats.chain_fingerprint) names WHAT computes; these name WHAT
# it computed OVER — a (plan_fp, content_digest) pair is hit-safe
# across process restarts because both halves are content-derived.
# ---------------------------------------------------------------------------

def part_signature(path: str) -> str:
    """Signature of one on-disk part file: sha256 over (basename, size,
    mtime_ns). A stat proxy, deliberately NOT a content hash — a
    growing-directory scan must be able to fingerprint a multi-GB table
    in O(#files) stat calls, and any rewrite bumps mtime_ns. The
    tradeoff is stated: a byte-level rewrite that preserves size and
    nanosecond mtime would serve stale (requires a deliberate
    ``touch -d``-style forgery; ordinary writes always move mtime_ns)."""
    st = os.stat(path)
    h = hashlib.sha256()
    h.update(os.path.basename(path).encode())
    h.update(b"|%d|%d" % (int(st.st_size), int(st.st_mtime_ns)))
    return h.hexdigest()[:24]


def content_digest(signatures: Iterable[str]) -> str:
    """Fold per-part signatures into one input-partition digest. Order-
    sensitive on purpose: the manifest order IS the row order, and a
    reordered directory is different input even when the part set
    matches."""
    h = hashlib.sha256(b"parts|")
    for sig in signatures:
        h.update(str(sig).encode())
        h.update(b"|")
    return h.hexdigest()[:32]


def frame_content_digest(frame) -> str:
    """Content digest of an in-memory frame (the static-source case of
    a registered query): schema + every block's bytes. Dense columns
    hash their buffer; host/object columns hash their repr — exact
    enough for cache keying (a repr collision between two DIFFERENT
    host columns would need colliding reprs, and host columns are
    strings/small objects here)."""
    h = hashlib.sha256(b"frame|")
    h.update(json.dumps(
        [(c.name, c.dtype.name) for c in frame.schema]
    ).encode())
    for block in frame.blocks():
        for name in sorted(block):
            v = block[name]
            h.update(name.encode() + b"|")
            if isinstance(v, list):
                h.update(repr(v).encode())
                continue
            arr = np.asarray(v)
            if arr.dtype == object:
                h.update(repr(arr.tolist()).encode())
            else:
                h.update(str((arr.shape, str(arr.dtype))).encode())
                h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:32]


def _scrub(text: str) -> str:
    """Drop process-local memory addresses from jaxpr text (function
    reprs inside callback/custom-primitive params embed them)."""
    return _ADDR_RE.sub("0x", text)


def _const_digest(h, const, include_values: bool,
                  value_policy: str) -> None:
    """Feed one traced constant into the running hash. ``value_policy``
    'host_only' skips device-array values (the lint surface must not
    trigger device→host transfers); 'all' hashes every value (the
    compile path — a transfer is noise next to the XLA compile)."""
    try:
        import jax

        is_device = isinstance(const, jax.Array)
    except Exception:  # pragma: no cover - jax always importable here
        is_device = False
    try:
        if include_values and (value_policy == "all" or not is_device):
            arr = np.asarray(const)
            h.update(str((arr.shape, str(arr.dtype))).encode())
            h.update(arr.tobytes())
        else:
            shape = getattr(const, "shape", None)
            dtype = getattr(const, "dtype", None)
            h.update(str((tuple(shape) if shape is not None else None,
                          str(dtype))).encode())
    except (TypeError, ValueError):
        # non-array capture: repr is the best available identity; if it
        # embeds process-local state, TFG108 is the rule that says so
        h.update(_scrub(repr(const)).encode())


def _env_parts(kind: str, donate: bool, hoisted: bool) -> Dict[str, object]:
    import jax

    from ..config import get_config
    from ..parallel.distributed import process_topology

    from .. import kernels as _kernels
    from ..plan import lift as _lift

    cfg = get_config()
    dev = jax.devices()[0]
    return {
        "format": FORMAT_VERSION,
        # kernel-selection state: pallas on/off (config switch AND the
        # runtime Mosaic kill-switch), the force hook, and interpreter
        # mode — any flip invalidates every key, because the lowering
        # the cost model picks is baked into the traced program
        "kernels": _kernels.fingerprint_token(),
        # verified-lift state: enabled flag + synthesis-rule version —
        # a lifted stage and its callback original trace to different
        # programs, so a TFTPU_LIFT flip must miss cleanly
        "lift": _lift.fingerprint_token(),
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "n_devices": jax.device_count(),
        # the full device→process topology, not just counts: one rank's
        # published executable must be every peer's hit, and a resized
        # or reshaped fleet must miss cleanly
        "topology": process_topology(),
        "x64": bool(jax.config.jax_enable_x64),
        "demote_x64": str(cfg.demote_x64_on_tpu),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        "kind": kind,
        "donate": bool(donate),
        "form": "hoisted" if hoisted else "plain",
    }


def _sharding_parts(avals, shardings) -> Dict[str, object]:
    """Per-input sharding descriptors keyed by input name. ``shardings``
    maps input name → sharding (or is None); descriptors normalize the
    trivial placement to None so unsharded keys are layout-free."""
    from ..parallel.mesh import sharding_descriptor

    out: Dict[str, object] = {}
    if not shardings:
        return out
    for (name, _, _) in avals:
        desc = sharding_descriptor(shardings.get(name))
        if desc is not None:
            out[str(name)] = desc
    return out


def _key_slots(
    closed,
    avals: Sequence[Tuple[str, Tuple[int, ...], str]],
    out_names: Sequence[str],
    *,
    kind: str,
    donate: bool,
    hoisted: bool,
    value_policy: str,
    shardings: Optional[Dict[str, object]],
    extra: Optional[Dict[str, object]],
) -> Dict[str, bytes]:
    """Every slot of the cache key, serialized ONCE. The composed hash
    (:func:`fingerprint_from_closed`) and the per-component digests
    (:func:`fingerprint_components`) both derive from this dict, so a
    slot added to one pipeline can never silently miss the other —
    TFG108 would otherwise report a program stable while the real store
    key moved."""
    ch = hashlib.sha256(b"consts:%d|" % len(closed.consts))
    for c in closed.consts:
        _const_digest(ch, c, include_values=not hoisted,
                      value_policy=value_policy)
    return {
        "jaxpr": _scrub(str(closed.jaxpr)).encode(),
        "consts": ch.digest(),
        "avals": json.dumps(
            [(n, list(s), d) for (n, s, d) in avals], sort_keys=True
        ).encode(),
        "outs": json.dumps(list(out_names)).encode(),
        "shardings": json.dumps(
            _sharding_parts(avals, shardings), sort_keys=True
        ).encode(),
        "env": json.dumps(
            _env_parts(kind, donate, hoisted), sort_keys=True
        ).encode(),
        "extra": json.dumps(extra or {}, sort_keys=True).encode(),
    }


def fingerprint_components(
    closed,
    avals: Iterable[Tuple[str, Tuple[int, ...], str]],
    out_names: Sequence[str],
    *,
    kind: str = "block",
    donate: bool = False,
    hoisted: bool = False,
    value_policy: str = "all",
    shardings: Optional[Dict[str, object]] = None,
    extra: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """The fingerprint's per-component digests: ``jaxpr``, ``consts``,
    ``avals``, ``outs``, ``env``, ``extra`` (each a short hex digest)
    plus ``shardings`` (a dict input-name → per-input descriptor
    digest). Two traces of a stable program agree on every component;
    TFG108 diffs the dicts to name exactly what moved."""
    avals = list(avals)
    slots = _key_slots(
        closed, avals, out_names, kind=kind, donate=donate,
        hoisted=hoisted, value_policy=value_policy,
        shardings=shardings, extra=extra,
    )
    out: Dict[str, object] = {
        name: hashlib.sha256(payload).hexdigest()[:16]
        for name, payload in slots.items()
        if name != "shardings"
    }
    # shardings stay per-input so TFG108 can name WHICH input's layout
    # moved (same _sharding_parts the composed slot serializes)
    out["shardings"] = {
        name: hashlib.sha256(
            json.dumps(desc, sort_keys=True).encode()
        ).hexdigest()[:16]
        for name, desc in _sharding_parts(avals, shardings).items()
    }
    return out


def fingerprint_from_closed(
    closed,
    avals: Iterable[Tuple[str, Tuple[int, ...], str]],
    out_names: Sequence[str],
    *,
    kind: str = "block",
    donate: bool = False,
    hoisted: bool = False,
    value_policy: str = "all",
    shardings: Optional[Dict[str, object]] = None,
    extra: Optional[Dict[str, object]] = None,
) -> str:
    """Fingerprint an already-traced program.

    ``closed`` is the ``ClosedJaxpr`` of the (possibly vmapped) entry
    function; ``avals`` the sorted (name, shape, dtype-str) triples of
    the feed the executable is specialized to; ``out_names`` the fetch
    order; ``shardings`` an optional input-name → sharding map (only
    non-trivial placements enter the key). Hoisted form excludes const
    *values* from the key (they are runtime arguments of the cached
    executable). ``extra`` is a JSON-able dict folded into the key for
    entry-specific identity the other slots don't carry (``aot_jit``
    puts its declared in/out sharding trees, label, and weak-type
    flags here).
    """
    slots = _key_slots(
        closed, list(avals), out_names, kind=kind, donate=donate,
        hoisted=hoisted, value_policy=value_policy,
        shardings=shardings, extra=extra,
    )
    h = hashlib.sha256()
    for name in sorted(slots):
        h.update(name.encode() + b":")
        h.update(slots[name])
        h.update(b"|")
    return h.hexdigest()[:40]


def program_fingerprint(
    program,
    probe: int = 8,
    *,
    kind: str = "block",
    donate: bool = False,
    hoisted: bool = False,
    value_policy: str = "host_only",
    mesh=None,
    shardings: Optional[Dict[str, object]] = None,
    components: bool = False,
):
    """Trace ``program`` fresh and fingerprint it (plain form by
    default — const values in the key, exactly what the executor uses
    when constant hoisting is off). Each call re-traces, so two calls
    on one program probe rebuild stability (TFG108). ``mesh`` installs
    the ambient mesh context for the trace (a sharded program must be
    probed exactly as the executor traces it — still zero device
    transfers: tracing is abstract and ``value_policy='host_only'``
    keeps device-resident captures out of the value hash).
    ``components=True`` returns the per-component digest dict
    (:func:`fingerprint_components`) instead of the composed hash.
    Returns None when the program cannot be traced."""
    import jax

    from ..parallel._shard_map import mesh_context
    from ..program import _abstract_inputs

    abstract = _abstract_inputs(program.inputs, probe)

    def rebuilt(feeds):
        # a fresh function object per call defeats jax's trace cache
        # (keyed on fn identity + avals): each fingerprint really does
        # re-run the user's capture logic, which is the whole point of
        # the TFG108 stability probe
        return program.fn(feeds)

    try:
        with mesh_context(mesh):
            closed = jax.make_jaxpr(rebuilt)(abstract)
    except Exception:
        return None
    avals = sorted(
        (name, tuple(int(d) for d in np.shape(a)), str(a.dtype))
        for name, a in abstract.items()
    )
    outs = list(program.fetch_order or [o.name for o in program.outputs])
    fn = fingerprint_components if components else fingerprint_from_closed
    return fn(
        closed, avals, outs, kind=kind, donate=donate, hoisted=hoisted,
        value_policy=value_policy, shardings=shardings,
    )
