"""Persistent AOT executable cache + warmup (ISSUE 5).

Every process used to pay the full trace + XLA compile on the first
dispatch per feed-shape key (2.8s for Inception-299, 1.4s for
BERT-base on the bench record — 20-40s on real TPUs), and the
executor's in-memory jit cache died with the process. This subsystem
makes compiled executables durable and shareable:

* :mod:`.fingerprint` — stable content hash of (jaxpr + consts +
  feed-shape bucket + dtype policy + backend/device + donation/hoist
  flags + jax version); no Python ``hash()``, survives restarts;
* :mod:`.store` — size-bounded on-disk executable store (CRC-checked,
  fsync-then-rename publish, LRU eviction) the executor consults on
  every jit-cache miss: hit ⇒ deserialize in milliseconds instead of
  compiling; any store problem degrades to a normal compile;
* :mod:`.warmup` — ``tfs.warmup(...)`` precompiles the expected shape
  buckets ahead of traffic, optionally replaying the store's recorded
  miss manifest;
* ``python -m tensorframes_tpu.compilecache`` — stats / warm / prune /
  verify (see docs/compilecache.md).

Disabled by default; ``TFTPU_COMPILE_CACHE=/dir`` (or
``configure(compilation_cache_dir=...)``) turns it on.
"""

from .fingerprint import FORMAT_VERSION, program_fingerprint  # noqa: F401
from .store import CompileCacheStore, active_store, store_for  # noqa: F401
from .warmup import (  # noqa: F401
    WarmupReport,
    decode_slot_buckets,
    decode_warmup_grid,
    partitioner_row_counts,
    serving_row_buckets,
    warm_program,
    warmup,
)

__all__ = [
    "FORMAT_VERSION",
    "CompileCacheStore",
    "WarmupReport",
    "active_store",
    "decode_slot_buckets",
    "decode_warmup_grid",
    "partitioner_row_counts",
    "program_fingerprint",
    "serving_row_buckets",
    "store_for",
    "warm_program",
    "warmup",
]
