"""Clean-room TensorFlow TensorBundle (checkpoint) reader.

A TF2 SavedModel stores its variable values as a *tensor bundle*:
``variables/variables.index`` (a LevelDB-style sorted string table
mapping tensor keys to ``BundleEntryProto`` records) plus one or more
``variables/variables.data-NNNNN-of-MMMMM`` shards holding the raw
tensor bytes. This module reads both with no TensorFlow dependency —
the last piece of the TF-free migration story (VERDICT r3 #9): a
variable-bearing SavedModel previously had to be frozen *via* TF at
conversion time (``core.py:42-56`` ≙ the freezing the reference
required of its users; ``graphdef.py`` ``load_saved_model`` fallback).

Wire formats implemented here (all public, stable TF formats):

* **SSTable** (``variables.index``): 48-byte footer (varint64 block
  handles + magic ``0xdb4775248b80fb57``), prefix-compressed blocks
  with a restart array, 1-byte compression tag per block (only raw,
  type 0, is produced for bundle indexes).
* **BundleEntryProto** (value of each index entry): dtype (field 1),
  TensorShapeProto (2), shard_id (3), offset (4), size (5), crc32c (6).
* **Bundle string tensors** (the ``_CHECKPOINTABLE_OBJECT_GRAPH``
  entry): per-element varint lengths, a 4-byte crc of the lengths,
  then the concatenated bytes.
* **TrackableObjectGraph** (the object graph tensor's payload): nodes
  (field 1) with attributes (field 2) = SerializedTensor {name=1,
  full_name=2, checkpoint_key=3} — the map from a variable's graph
  name to its checkpoint key.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Tuple

import numpy as np

_FOOTER_MAGIC = bytes.fromhex("57fb808b247547db")  # little-endian magic


class BundleError(ValueError):
    """Raised for malformed bundle files (callers may fall back)."""


def _read_varint(b: bytes, p: int) -> Tuple[int, int]:
    x = 0
    s = 0
    while True:
        if p >= len(b):
            raise BundleError("truncated varint")
        c = b[p]
        p += 1
        x |= (c & 0x7F) << s
        if not c & 0x80:
            return x, p
        s += 7


def _iter_fields(b: bytes):
    p = 0
    while p < len(b):
        tag, p = _read_varint(b, p)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, p = _read_varint(b, p)
        elif wire == 2:
            ln, p = _read_varint(b, p)
            v = b[p : p + ln]
            p += ln
        elif wire == 5:
            v = b[p : p + 4]
            p += 4
        elif wire == 1:
            v = b[p : p + 8]
            p += 8
        else:
            raise BundleError(f"unsupported wire type {wire}")
        yield field, wire, v


def _parse_table_block(data: bytes, off: int, size: int) -> List[Tuple[bytes, bytes]]:
    """Decode one SSTable block (prefix-compressed entries + restart
    array). The byte at ``data[off+size]`` is the compression tag —
    bundle index blocks are written raw (type 0)."""
    # ``>=``: the compression-tag byte at data[off+size] must itself be
    # in range, else a truncated index crashes with IndexError instead
    # of the BundleError the fallback contract documents (ADVICE r4)
    if off + size >= len(data):
        raise BundleError("block handle past end of file")
    if size < 4:
        raise BundleError("block too small for a restart array")
    if data[off + size] != 0:
        raise BundleError(
            f"compressed index block (type {data[off + size]}) — bundle "
            "indexes are written uncompressed"
        )
    raw = data[off : off + size]
    n_restarts = struct.unpack("<I", raw[-4:])[0]
    limit = len(raw) - 4 * (n_restarts + 1)
    if limit < 0:
        raise BundleError("restart array larger than block")
    entries: List[Tuple[bytes, bytes]] = []
    p = 0
    key = b""
    while p < limit:
        shared, p = _read_varint(raw, p)
        unshared, p = _read_varint(raw, p)
        vlen, p = _read_varint(raw, p)
        key = key[:shared] + raw[p : p + unshared]
        p += unshared
        entries.append((key, raw[p : p + vlen]))
        p += vlen
    return entries


def _parse_shape(data: bytes) -> List[int]:
    dims: List[int] = []
    for field, _, v in _iter_fields(data):
        if field == 2:
            size = 0
            for f2, _, v2 in _iter_fields(v):
                if f2 == 1:
                    size = v2
            dims.append(int(size))
    return dims


# types.proto DataType enum → numpy dtype for the bundle payloads
_BUNDLE_DTYPES = {
    1: np.float32,
    2: np.float64,
    3: np.int32,
    4: np.uint8,
    6: np.int8,
    9: np.int64,
    10: np.bool_,
    19: np.float16,
}
try:  # bfloat16 payloads need ml_dtypes (bundled with jax)
    import ml_dtypes as _mld

    _BUNDLE_DTYPES[14] = _mld.bfloat16
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    pass
_DT_STRING = 7


class BundleEntry:
    __slots__ = ("dtype_enum", "shape", "shard_id", "offset", "size")

    def __init__(self, value: bytes):
        self.dtype_enum = 0
        self.shape: List[int] = []
        self.shard_id = 0
        self.offset = 0
        self.size = 0
        for field, _, v in _iter_fields(value):
            if field == 1:
                self.dtype_enum = int(v)
            elif field == 2:
                self.shape = _parse_shape(v)
            elif field == 3:
                self.shard_id = int(v)
            elif field == 4:
                self.offset = int(v)
            elif field == 5:
                self.size = int(v)


def read_index(index_path: str) -> Dict[str, BundleEntry]:
    """Parse ``variables.index`` into ``{tensor_key: BundleEntry}``."""
    with open(index_path, "rb") as f:
        data = f.read()
    if len(data) < 48 or data[-8:] != _FOOTER_MAGIC:
        raise BundleError(f"{index_path}: not a tensor-bundle index")
    footer = data[-48:-8]
    p = 0
    _meta_off, p = _read_varint(footer, p)
    _meta_size, p = _read_varint(footer, p)
    idx_off, p = _read_varint(footer, p)
    idx_size, p = _read_varint(footer, p)
    entries: Dict[str, BundleEntry] = {}
    for _, handle in _parse_table_block(data, idx_off, idx_size):
        boff, q = _read_varint(handle, 0)
        bsize, q = _read_varint(handle, q)
        for key, value in _parse_table_block(data, boff, bsize):
            if key == b"":
                continue  # BundleHeaderProto (num_shards/endianness)
            entries[key.decode("utf-8")] = BundleEntry(value)
    return entries


def _shard_path(prefix: str, shard_id: int, num_shards: int) -> str:
    return f"{prefix}.data-{shard_id:05d}-of-{num_shards:05d}"


def _read_entry(prefix: str, entry: BundleEntry, num_shards: int):
    path = _shard_path(prefix, entry.shard_id, num_shards)
    with open(path, "rb") as f:
        f.seek(entry.offset)
        raw = f.read(entry.size)
    if len(raw) != entry.size:
        raise BundleError(f"{path}: truncated read at {entry.offset}")
    if entry.dtype_enum == _DT_STRING:
        n = int(np.prod(entry.shape)) if entry.shape else 1
        lens = []
        p = 0
        for _ in range(n):
            ln, p = _read_varint(raw, p)
            lens.append(ln)
        p += 4  # crc32c of the lengths
        out = np.empty(n, object)
        for i, ln in enumerate(lens):
            out[i] = raw[p : p + ln]
            p += ln
        return out.reshape(entry.shape) if entry.shape else out[0]
    np_dt = _BUNDLE_DTYPES.get(entry.dtype_enum)
    if np_dt is None:
        raise BundleError(
            f"bundle tensor dtype enum {entry.dtype_enum} unsupported"
        )
    arr = np.frombuffer(raw, np_dt)
    return arr.reshape(entry.shape)


def _object_graph_name_map(og_bytes: bytes) -> Dict[str, str]:
    """TrackableObjectGraph → ``{variable full_name: checkpoint_key}``."""
    mapping: Dict[str, str] = {}
    for field, _, node in _iter_fields(og_bytes):
        if field != 1:
            continue
        for f2, _, attr in _iter_fields(node):
            if f2 != 2:  # attributes: SerializedTensor
                continue
            full = key = None
            for f3, _, v3 in _iter_fields(attr):
                if f3 == 2 and isinstance(v3, bytes):
                    full = v3.decode("utf-8")
                elif f3 == 3 and isinstance(v3, bytes):
                    key = v3.decode("utf-8")
            if key and full:
                mapping[full] = key
    return mapping


_OBJECT_GRAPH_KEY = "_CHECKPOINTABLE_OBJECT_GRAPH"
_VAR_SUFFIX = "/.ATTRIBUTES/VARIABLE_VALUE"


def restore_variables(variables_dir: str) -> Dict[str, np.ndarray]:
    """Read every variable in a SavedModel's ``variables/`` directory,
    keyed by the VARIABLE NAME the graph's ``VarHandleOp`` nodes carry
    (``shared_name``), with the bare checkpoint keys as a fallback
    alias. TF-free at conversion AND scoring time."""
    prefix = os.path.join(variables_dir, "variables")
    entries = read_index(prefix + ".index")
    # num_shards: derive from the shard files present (header says too,
    # but the filesystem is authoritative for what we can read)
    num_shards = 1
    for name in os.listdir(variables_dir):
        if name.startswith("variables.data-"):
            num_shards = int(name.rsplit("-", 1)[1])
            break
    name_map: Dict[str, str] = {}
    if _OBJECT_GRAPH_KEY in entries:
        og = _read_entry(prefix, entries[_OBJECT_GRAPH_KEY], num_shards)
        og_bytes = og if isinstance(og, bytes) else bytes(og)
        name_map = _object_graph_name_map(og_bytes)
    out: Dict[str, np.ndarray] = {}
    for key, entry in entries.items():
        if key == _OBJECT_GRAPH_KEY or entry.dtype_enum == _DT_STRING:
            continue
        value = _read_entry(prefix, entry, num_shards)
        out[key] = value
        if key.endswith(_VAR_SUFFIX):
            out.setdefault(key[: -len(_VAR_SUFFIX)], value)
    # the object graph's full_name is the graph-side variable name for
    # keras-style models whose checkpoint keys are object paths
    # (layer_with_weights-0/kernel/…) rather than variable names
    for full, key in name_map.items():
        if key in out:
            out.setdefault(full, out[key])
    return out
