"""The TensorFrame: a block-partitioned columnar container.

This is the TPU-native replacement for the reference's Spark ``DataFrame``
(+ the tensor metadata it smuggles into ``StructField``\\ s). A frame is a
list of *blocks* (≙ Spark partitions); each block maps column name →

* a dense ``numpy.ndarray`` with leading row dim (device columns), or
* a Python list of per-row cells (ragged columns awaiting ``analyze`` /
  per-row execution, and host-only string/binary columns,
  ≙ datatypes.scala:571-622).

Verbs are **lazy**, like the reference's map verbs under Spark
(core.py:232-233 "the result is lazy and will not be computed until
requested"): ``map_*`` returns a frame carrying a pending computation;
``collect()`` / ``blocks()`` forces it once and caches. Chained lazy
maps record a logical plan (:mod:`tensorframes_tpu.plan`) and each
maximal fusable run lowers to a SINGLE composed XLA program dispatched
once per block — a fusion win the reference structurally could not get
across two Spark stages (``TFTPU_FUSION=0`` restores per-stage
execution; results are bit-identical either way).

Shape discovery parity:

* ``analyze``  ≙ ExperimentalOperations.deepAnalyzeDataFrame
  (ExperimentalOperations.scala:89-132): full scan, per-cell recursive
  shapes, pointwise merge (disagreement → Unknown), block sizes prepended.
* ``append_shape`` ≙ ExperimentalOperations.appendShape (:53-68).
* ``print_schema`` / ``explain`` ≙ DebugRowOps.explain (:535-552).
* scalar columns need no analysis (ColumnInformation.extractFromRow,
  ColumnInformation.scala:124-138); list columns start with Unknown dims —
  the ArrayType recursion prepending Unknown.
"""

from __future__ import annotations

import dataclasses as _dataclasses
import functools as _functools
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import dtypes as dt
from .config import get_config
from .schema import ColumnInfo, Schema
from .shape import Shape, Unknown, shape_of_nested
import time

from .utils import get_logger
from .utils import profiling

logger = get_logger(__name__)

# One block: column name -> dense ndarray (lead dim = rows) or list of cells.
Block = Dict[str, Union[np.ndarray, list]]


def _non_addressable(v) -> bool:
    """True for a jax Array whose shards span processes (no process can
    materialize it alone)."""
    return (
        hasattr(v, "is_fully_addressable") and not v.is_fully_addressable
    )


def _spanned(name: str, compute, rows_fn):
    """Wrap a pending thunk so forcing it records a profiling span.
    ``rows_fn()`` supplies the INPUT row count at force time — the same
    convention as the verbs (a filter that keeps 10 of 1M rows did 1M
    rows of work, and report() throughputs must stay comparable)."""

    def run():
        t0 = time.perf_counter()
        blocks = compute()
        profiling.record(name, time.perf_counter() - t0, rows_fn())
        return blocks

    return run


# ADVICE r5: crossing config.relational_broadcast_bytes silently flips
# the multi-process sort_values result LAYOUT — under the budget every
# process holds the full replicated sorted frame, over it each process
# holds only its key range. Programs written against the replicated
# contract must get a runtime signal the first time the switch happens,
# not discover it from collect()'s row count.
_SORT_LAYOUT_LOCK = threading.Lock()
_sort_layout_warned = False


def _warn_sort_layout_switch(gbytes: int, budget: int) -> None:
    """One-time (per process) tripwire for the replicated → range-
    partitioned sort_values layout switch."""
    global _sort_layout_warned
    with _SORT_LAYOUT_LOCK:
        if _sort_layout_warned:
            return
        _sort_layout_warned = True
    logger.warning(
        "sort_values: frame (%s bytes global) exceeds "
        "config.relational_broadcast_bytes (%s) — switching from the "
        "REPLICATED plan to the range-partitioned exchange: each "
        "process now holds only ITS key range (O(global/P) rows), not "
        "the full sorted frame. collect()/num_rows are per-process "
        "under this layout; concatenating the processes' results in "
        "process order is the global sort order. Raise the budget "
        "(TFTPU_RELATIONAL_BROADCAST_MB) to keep the replicated "
        "contract. (This tripwire fires once per process.)",
        f"{gbytes:,}", f"{budget:,}",
    )


def _replicated_fleetwide(cols: Dict[str, Union[np.ndarray, list]]) -> bool:
    """True when EVERY process holds byte-identical local columns (a
    full-content 128-bit blake2b over every column — values, dtypes,
    shapes — allgathered and compared; a collision-prone 32-bit CRC
    would let two different frames silently pass as replicated).
    Judged on ALL columns, not just keys: a process-local frame whose
    key column coincides fleet-wide (e.g. b=[7,7] everywhere after a
    repartition on a) is NOT replicated, and deduping it locally would
    silently keep cross-process duplicates — the exact r5-review
    hazard. The branch taken is uniform fleet-wide: every process
    enters the one allgather, including processes whose local hash
    failed (their signature marks not-ok instead of skipping the
    collective). Single-process programs are trivially replicated.
    Used by drop_duplicates for replicated-in → replicated-out
    semantics (ADVICE r5)."""
    import jax

    if jax.process_count() == 1:
        return True
    import hashlib

    from jax.experimental import multihost_utils as _mh

    def _hash_col(h, v) -> None:
        cells = v if isinstance(v, list) else [v]
        for c in cells:
            a = np.asarray(c)
            h.update(str((a.dtype.str, a.shape)).encode())
            h.update(
                str(a.tolist()).encode() if a.dtype == object
                else np.ascontiguousarray(a).tobytes()
            )

    h, ok = hashlib.blake2b(digest_size=16), 1
    try:
        for name in sorted(cols):
            h.update(name.encode())
            _hash_col(h, cols[name])
    except Exception:  # unhashable layout: the exchange is the safe path
        ok = 0
    digest = np.frombuffer(h.digest(), dtype="<i8")  # 2 x int64
    sig = np.asarray([np.int64(ok), digest[0], digest[1]])
    sigs = np.asarray(_mh.process_allgather(sig)).reshape(-1, 3)
    return (
        all(int(r[0]) == 1 for r in sigs)
        and len({(int(r[1]), int(r[2])) for r in sigs}) == 1
    )


def _gathered_local_or_raise(frame, names, op_name: str):
    """This process's rows of ``names`` with the fleet-wide eligibility
    VOTE (one collective): every process must gather successfully or
    every process raises — one process bailing out of a later
    collective its peers already entered would deadlock the fleet.
    Shared by the exchange-planning verbs (sort_values /
    drop_duplicates / repartition_by_key)."""
    from .ops.device_agg import gather_local_columns, uniform_ok

    local = gather_local_columns(frame, names)
    if not uniform_ok(local is not None):
        raise RuntimeError(
            f"{op_name}: some process holds no addressable shard of a "
            "column — re-shard so every process holds rows "
            "(frame_from_process_local)"
        )
    return local


def _merged_global_columns(
    frame, names, op_name: str, keep_device: bool = False
) -> Dict[str, object]:
    """Concatenate every block of ``names`` into single host/device
    columns — the global-materialization step shared by sort_values and
    join. Raises the actionable spans-processes guidance for
    multi-process frames. ``keep_device=True`` leaves fully-device
    columns as ``jax.Array``s (concatenated in HBM) instead of pulling
    them to host numpy — the device-sort path depends on it."""
    out: Dict[str, object] = {}
    blocks = frame.blocks()
    for name in names:
        vals = [b[name] for b in blocks]
        if any(_non_addressable(v) for v in vals):
            raise RuntimeError(
                f"{op_name}: columns span processes — one process cannot "
                f"materialize the global frame. {op_name} before "
                "frame_from_process_local, or reduce with a verb (verbs "
                "run as collectives)."
            )
        if any(isinstance(v, list) for v in vals):
            out[name] = [x for v in vals for x in v]
        elif keep_device and all(_is_jax_array(v) for v in vals):
            if len(vals) == 1:
                out[name] = vals[0]
            else:
                import jax.numpy as jnp

                out[name] = jnp.concatenate(vals)
        else:
            arrs = [np.asarray(v) for v in vals]
            out[name] = arrs[0] if len(arrs) == 1 else np.concatenate(arrs)
    return out


def _is_jax_array(v) -> bool:
    import jax

    return isinstance(v, jax.Array)


def _device_sort_codes(a, ascending: bool):
    """Map one device key column to a monotone SIGNED-INT code column so
    ``jnp.lexsort`` totally orders it on device (lax.sort underneath —
    the TPU-first sort the r3 verdict asked for, DebugRowOps.scala:583).

    * ints pass through (unsigned widens to int64; uint64 is rejected by
      the caller — it cannot widen);
    * bools become int8;
    * floats use the IEEE-754 radix trick in its SIGNED form (positive
      patterns keep their bits, negative patterns reflect about INT_MIN)
      — a total order matching numpy's sort order (-inf < … < +inf <
      NaN for the canonical positive-NaN);
    * descending applies bitwise NOT (monotone decreasing, no overflow,
      and lexsort's stability keeps tie order — negation would not
      survive int64 min).
    """
    import jax.numpy as jnp
    from jax import lax

    if a.dtype == jnp.bool_:
        k = a.astype(jnp.int8)
    elif jnp.issubdtype(a.dtype, jnp.unsignedinteger):
        k = a.astype(jnp.int64)
    elif jnp.issubdtype(a.dtype, jnp.integer):
        k = a
    else:  # floating
        if a.dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
            a = a.astype(jnp.float32)
        int_dt = jnp.int64 if a.dtype == jnp.dtype(jnp.float64) else jnp.int32
        # canonicalize NaNs first: a SIGN-BIT NaN (0xFFC… — what x86
        # 0.0/0.0 produces) would otherwise reflect to a hugely negative
        # code and sort FIRST, where numpy (and the host path) sort
        # every NaN last
        a = jnp.where(jnp.isnan(a), jnp.asarray(jnp.nan, a.dtype), a)
        bits = lax.bitcast_convert_type(a, int_dt)
        int_min = jnp.asarray(jnp.iinfo(int_dt).min, int_dt)
        # bits >= 0 (positive floats, +NaN): already monotone signed.
        # bits < 0 (negative floats): signed bits DEcrease as the float
        # increases toward -0, so reflect: int_min - bits is negative,
        # monotone increasing, and cannot overflow (bits = int_min maps
        # to exactly 0, the same key as +0.0 — they compare equal).
        k = jnp.where(bits >= 0, bits, int_min - bits)
    return ~k if not ascending else k


def _block_num_rows(block: Block) -> int:
    for v in block.values():
        return len(v)
    return 0


def _nested_depth(x) -> int:
    d = 0
    while isinstance(x, (list, tuple)) or (isinstance(x, np.ndarray) and x.ndim > 0):
        if isinstance(x, np.ndarray):
            return d + x.ndim
        if len(x) == 0:
            return d + 1
        d += 1
        x = x[0]
    return d


def _leaf_value(x):
    while isinstance(x, (list, tuple)) and len(x) > 0:
        x = x[0]
    if isinstance(x, np.ndarray):
        while x.ndim > 0:
            if x.shape[0] == 0:
                return x.dtype.type(0)
            x = x[0]
        return x
    return x


# ---------------------------------------------------------------------------
# hash-join core: module-level so the plan lowering (plan/lower.py) runs
# EXACTLY the join the eager path runs — the two cannot diverge
# ---------------------------------------------------------------------------

@_dataclasses.dataclass(frozen=True)
class _JoinSpec:
    """Normalized description of one hash join, detached from the frames.

    ``lname``/``rname`` map each side's non-key columns to their output
    names (clash suffixes already applied); pair order is output order.
    :func:`_hash_join_cols` joins whatever subset of those columns is
    present in its inputs — the plan's needed-columns pass prunes
    THROUGH the join by simply not materializing dead columns."""

    keys: Tuple[str, ...]
    how: str  # 'inner' | 'left' | 'outer' ('right' mirrors to 'left')
    lname: Tuple[Tuple[str, str], ...]  # (original, output) left pairs
    rname: Tuple[Tuple[str, str], ...]
    fill_value: object = None

    def fill_for(self, col_name):
        if isinstance(self.fill_value, dict):
            if col_name not in self.fill_value:
                raise ValueError(
                    f"how={self.how!r}: fill_value has no entry for "
                    f"column {col_name!r}"
                )
            return self.fill_value[col_name]
        return self.fill_value

    def checked_fill(self, col_name, np_dtype):
        """The fill cast must be EXACT — a lossy fill (e.g. -1.5 into an
        int column) would corrupt silently, the very failure mode
        mandatory fills exist to prevent."""
        fv = self.fill_for(col_name)
        try:
            cast = np.asarray(fv, np_dtype)
        except (ValueError, TypeError, OverflowError):
            # e.g. NaN fill into an int column: numpy raises its own
            # 'cannot convert float NaN to integer' before the
            # representability check below can phrase it usefully
            raise ValueError(
                f"how={self.how!r}: fill_value {fv!r} is not exactly "
                f"representable in column {col_name!r}'s dtype "
                f"{np_dtype}"
            ) from None
        same = (
            cast != cast and fv != fv  # NaN fill into a float col
        ) or cast == np.asarray(fv)
        if not bool(same):
            raise ValueError(
                f"how={self.how!r}: fill_value {fv!r} is not exactly "
                f"representable in column {col_name!r}'s dtype "
                f"{np_dtype}"
            )
        return cast


def _key_union_col(lv, rv):
    """Concatenate one key column's two sides into the array form the
    group encoder accepts (host list / object columns promote to object
    arrays). THE single union construction for key-membership encoding:
    `_hash_join_cols` and the plan's pushed-down semi-join filter
    (plan/lower.py) both build unions here, so their NaN/string
    semantics — and with them the pushdown's bit-identity contract —
    cannot drift apart."""
    if isinstance(lv, list) or isinstance(rv, list) or (
        getattr(lv, "dtype", None) == object
        or getattr(rv, "dtype", None) == object
    ):
        u = np.empty(len(lv) + len(rv), dtype=object)
        u[: len(lv)] = list(lv)
        u[len(lv):] = list(rv)
        return u
    return np.concatenate([np.asarray(lv), np.asarray(rv)])


def _hash_join_cols(
    lcols: Dict[str, object], rcols: Dict[str, object], spec: _JoinSpec
) -> Block:
    """Join two gathered column dicts per ``spec``. Key encoding rides
    the aggregate machinery (``ops/keys.py``); the match expansion is
    fully vectorized. Result ordering is pandas-like: left-row order,
    ties in the right frame's stable order; ``outer`` appends unmatched
    right rows in right order. Only the non-key columns PRESENT in
    ``lcols``/``rcols`` are joined (plan pushdown prunes the rest)."""
    from .ops.keys import group_ids

    keys, how = list(spec.keys), spec.how
    lname = {c: o for c, o in spec.lname if c in lcols}
    rname = {c: o for c, o in spec.rname if c in rcols}
    left_only = list(lname)
    right_only = list(rname)
    nl = _block_num_rows({k: lcols[k] for k in keys})
    nr = _block_num_rows({k: rcols[k] for k in keys})
    if (nl == 0 and how != "outer") or (
        nr == 0 and how == "inner"
    ) or (nl == 0 and nr == 0):
        # group_ids cannot encode zero rows; an empty side means an
        # empty inner join (left/outer joins keep the populated side's
        # rows via the branches below)
        out0: Block = {}
        for k in keys:
            v = lcols[k]
            out0[k] = [] if isinstance(v, list) else v[:0]
        for c in left_only:
            v = lcols[c]
            out0[lname[c]] = [] if isinstance(v, list) else v[:0]
        for c in right_only:
            v = rcols[c]
            out0[rname[c]] = [] if isinstance(v, list) else v[:0]
        return out0
    if nl == 0:  # outer join, only right rows: left cols filled
        out0 = {}
        for k in keys:
            out0[k] = rcols[k]
        for c in left_only:
            v = lcols[c]
            if isinstance(v, list):
                out0[lname[c]] = [spec.fill_for(c)] * nr
            else:
                out0[lname[c]] = np.full(
                    (nr,) + v.shape[1:],
                    spec.checked_fill(c, v.dtype),
                    v.dtype,
                )
        for c in right_only:
            out0[rname[c]] = rcols[c]
        return out0
    if nr == 0:
        # left join against an empty right side: all left rows, right
        # columns fully filled
        out0 = {}
        for k in keys:
            out0[k] = lcols[k]
        for c in left_only:
            out0[lname[c]] = lcols[c]
        for c in right_only:
            v = rcols[c]
            if isinstance(v, list):
                out0[rname[c]] = [spec.fill_for(c)] * nl
            else:
                out0[rname[c]] = np.full(
                    (nl,) + v.shape[1:], spec.checked_fill(c, v.dtype),
                    v.dtype,
                )
        return out0
    key_union = [_key_union_col(lcols[k], rcols[k]) for k in keys]
    codes, _, num_codes = group_ids(key_union)
    l_codes, r_codes = codes[:nl], codes[nl:]

    order_r = np.argsort(r_codes, kind="stable")
    counts = np.bincount(r_codes, minlength=num_codes)
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    cnt_l = counts[l_codes]
    if how in ("left", "outer"):
        # unmatched left rows still emit ONE output row, marked ri = -1
        # so right columns take the fill
        cnt_eff = np.maximum(cnt_l, 1)
    else:
        cnt_eff = cnt_l
    li = np.repeat(np.arange(nl), cnt_eff)
    total = int(cnt_eff.sum())
    offs = np.arange(total) - np.repeat(
        np.cumsum(cnt_eff) - cnt_eff, cnt_eff
    )
    base = np.repeat(starts[l_codes], cnt_eff) + offs
    if how in ("left", "outer"):
        matched = np.repeat(cnt_l > 0, cnt_eff)
        safe = np.where(
            matched, np.clip(base, 0, max(nr - 1, 0)), 0
        )
        ri = np.where(matched, order_r[safe], -1)
    else:
        ri = order_r[base]  # inner: every expansion matched

    def gather(col, idx):
        if isinstance(col, list):
            return [col[i] for i in idx]
        return col[idx]

    def gather_right(col, col_name):
        if how not in ("left", "outer"):
            return gather(col, ri)
        fv = spec.fill_for(col_name)
        if isinstance(col, list):
            return [col[i] if i >= 0 else fv for i in ri]
        safe_i = np.clip(ri, 0, None)
        # condition broadcasts across the cell dims of multi-dim
        # columns (embeddings etc.)
        cond = (ri >= 0).reshape((-1,) + (1,) * (col.ndim - 1))
        return np.where(
            cond, col[safe_i], spec.checked_fill(col_name, col.dtype)
        )

    out: Block = {}
    for k in keys:
        out[k] = gather(lcols[k], li)
    for c in left_only:
        out[lname[c]] = gather(lcols[c], li)
    for c in right_only:
        out[rname[c]] = gather_right(rcols[c], c)
    if how == "outer":
        # append the right rows NO left row matched (pandas sort=False
        # outer: they follow the left-ordered part, in right order),
        # left columns filled
        matched_r = np.zeros(nr, bool)
        matched_r[ri[ri >= 0]] = True
        extra = np.flatnonzero(~matched_r)
        if len(extra):
            def cat(a, b):
                if isinstance(a, list) or isinstance(b, list):
                    return list(a) + list(b)
                return np.concatenate([a, b])

            for k in keys:
                out[k] = cat(out[k], gather(rcols[k], extra))
            ne = len(extra)
            for c in left_only:
                v = lcols[c]
                if isinstance(v, list):
                    fills = [spec.fill_for(c)] * ne
                else:
                    fills = np.full(
                        (ne,) + v.shape[1:],
                        spec.checked_fill(c, v.dtype),
                        v.dtype,
                    )
                out[lname[c]] = cat(out[lname[c]], fills)
            for c in right_only:
                out[rname[c]] = cat(
                    out[rname[c]], gather(rcols[c], extra)
                )
    return out


class TensorFrame:
    """A lazy, block-partitioned columnar frame."""

    def __init__(
        self,
        blocks: Optional[List[Block]],
        schema: Schema,
        pending: Optional[Callable[[], List[Block]]] = None,
    ):
        if blocks is None and pending is None:
            raise ValueError("TensorFrame needs blocks or a pending computation")
        self._blocks = blocks
        self._pending = pending
        self.schema = schema
        # serializes first materialization: concurrent consumers (e.g. the
        # prefetch loader's worker + the main thread) force the pending
        # computation exactly once (≙ the reference's thread-safety is
        # Spark's task model; here it's the frame's own contract)
        self._force_lock = threading.Lock()

    # -- materialization ----------------------------------------------------
    def blocks(self) -> List[Block]:
        """Force and cache the frame's blocks (thread-safe, exactly once)."""
        if self._blocks is None:
            with self._force_lock:
                if self._blocks is None:
                    self._blocks = self._pending()
                    self._pending = None
                    # a recorded logical plan is spent once the blocks
                    # exist: drop it so the node chain (and through it
                    # the source frame's buffers) isn't pinned for this
                    # frame's lifetime. Downstream lazy chains hold
                    # their own node references and re-root here via
                    # is_materialized, never through this attribute.
                    self._plan = None
        return self._blocks

    @property
    def is_materialized(self) -> bool:
        return self._blocks is not None

    # -- basic accessors ----------------------------------------------------
    @property
    def num_blocks(self) -> int:
        return len(self.blocks())

    @property
    def num_rows(self) -> int:
        return sum(_block_num_rows(b) for b in self.blocks())

    @property
    def estimated_rows(self) -> Optional[int]:
        """Row-count estimate that NEVER forces a lazy frame: exact for
        materialized frames; a lazy chain rooted on a materialized
        source estimates the source's rows when no recorded node can
        change the row count (maps and selects preserve it; filters,
        joins, and aggregates are data-dependent). None when unknowable
        pre-force. The plan cost model's join-order decision records
        this (schema-derived estimate, refined by the stats sidecar's
        observed cardinalities — ISSUE 14)."""
        if self.is_materialized:
            return self.num_rows
        node = getattr(self, "_plan", None)
        if node is None:
            return None
        from .plan.ir import resolve_chain

        source, nodes = resolve_chain(node)
        if any(n.kind not in ("map", "select") for n in nodes):
            return None
        if getattr(source, "is_materialized", False):
            return source.num_rows
        return None

    @property
    def estimated_bytes(self) -> Optional[int]:
        """Host-byte estimate of materializing this frame (never forces
        a lazy chain): ``estimated_rows`` × the schema's per-row dense
        width (Unknown cell dims count as 1 — a lower bound). None when
        the row count is unknowable pre-force. TFG111 compares this
        against the block-store budget to flag larger-than-budget
        ``to_host``/``to_numpy`` materializations."""
        from .plan.lower import estimate_materialized_bytes

        return estimate_materialized_bytes(self)

    def spill_to(self, store) -> "object":
        """Spill this frame's blocks into a
        :class:`~tensorframes_tpu.blockstore.BlockStore` and return the
        :class:`~tensorframes_tpu.blockstore.SpilledFrame` handle
        (blocks past the store's budget land on disk; ``to_frame``
        rebuilds over memmap views). Forces a lazy chain block by
        block's result — multi-host global arrays are refused exactly
        like ``save_frame`` (no process can materialize them alone)."""
        from .blockstore.partitioner import SpilledFrame

        refs = []
        for b in self.blocks():
            host_b = {}
            for name, v in b.items():
                if _non_addressable(v):
                    raise ValueError(
                        f"spill_to: column {name!r} spans non-addressable "
                        "devices (multi-host global array); use "
                        "save_frame_sharded instead"
                    )
                host_b[name] = v if isinstance(v, list) else np.asarray(v)
            refs.append(store.put(host_b))
        return SpilledFrame(store, refs, self.schema)

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    def __repr__(self) -> str:
        state = "materialized" if self.is_materialized else "lazy"
        return f"TensorFrame({state}, {self.schema!r})"

    # -- conversions --------------------------------------------------------
    def column_values(self, name: str) -> np.ndarray:
        """Concatenate one column across blocks (dense columns only)."""
        info = self.schema[name]
        import jax as _jax

        if name in getattr(self, "_process_local_cols", ()) and _jax.process_count() > 1:
            raise RuntimeError(
                f"Column {name!r} is process-local (host-only column of a "
                "multi-process frame); one process cannot materialize the "
                "global column. Aggregate by it (the dictionary plan "
                "merges per-process key dictionaries with a collective), "
                "or persist per process with io.save_frame_sharded."
            )
        parts = []
        for b in self.blocks():
            v = b[name]
            if isinstance(v, list):
                v = np.asarray(v, dtype=object) if not info.is_device else np.asarray(v)
            elif not getattr(v, "is_fully_addressable", True):
                raise RuntimeError(
                    f"Column {name!r} spans processes (multi-host global "
                    "array); one process cannot materialize it. Reduce it "
                    "with a verb (reduce_blocks/reduce_rows/aggregate run "
                    "as collectives without a host gather), or persist per "
                    "process with io.save_frame_sharded."
                )
            parts.append(v)
        if not parts:
            return np.empty((0,), dtype=info.dtype.np_dtype)
        return np.concatenate(parts, axis=0)

    def collect(self) -> List[Dict[str, object]]:
        """Materialize as a list of row dicts (≙ ``DataFrame.collect``).

        Vector cells come back as numpy arrays; scalars as Python scalars.
        """
        from . import native

        rows: List[Dict[str, object]] = []
        for b in self.blocks():
            n = _block_num_rows(b)
            cols = {}
            for name in self.schema.names:
                v = b[name]
                if not isinstance(v, list):
                    v = np.asarray(v)  # device arrays come back in one copy
                cols[name] = v
            if all(
                isinstance(v, np.ndarray)
                and v.ndim == 1
                and native.supported_dtype(v.dtype)
                for v in cols.values()
            ):
                # native fast path: all-scalar blocks materialize as row
                # dicts in one C++ pass (≙ convertBackFast0,
                # DataOps.scala:20-61)
                native_rows = native.columns_to_rows(
                    list(cols.keys()), list(cols.values())
                )
                if native_rows is not None:
                    rows.extend(native_rows)
                    continue
            for i in range(n):
                row = {}
                for name, v in cols.items():
                    cell = v[i]
                    if isinstance(cell, np.ndarray) and cell.ndim == 0:
                        cell = cell.item()
                    elif isinstance(cell, np.generic):
                        cell = cell.item()
                    row[name] = cell
                rows.append(row)
        return rows

    def take(self, n: int) -> List[Dict[str, object]]:
        """First ``n`` rows as dicts without materializing later blocks'
        columns to rows (≙ ``DataFrame.take``)."""
        out: List[Dict[str, object]] = []
        for b in self.blocks():
            m = _block_num_rows(b)
            if m == 0:
                continue
            take_here = min(n - len(out), m)
            small = TensorFrame(
                [{k: v[:take_here] for k, v in b.items()}], self.schema
            )
            out.extend(small.collect())
            if len(out) >= n:
                break
        return out

    def first(self) -> Dict[str, object]:
        for b in self.blocks():
            if _block_num_rows(b) > 0:
                row = {}
                for name in self.schema.names:
                    cell = b[name][0]
                    if not isinstance(cell, (list, str, bytes)):
                        cell = np.asarray(cell)  # incl. device arrays
                        cell = cell.item() if cell.ndim == 0 else cell
                    row[name] = cell
                return row
        raise ValueError("Frame is empty")

    def to_pandas(self):
        import pandas as pd

        data = {}
        for name in self.schema.names:
            vals = []
            for b in self.blocks():
                v = b[name]
                if not isinstance(v, (list, np.ndarray)):
                    v = np.asarray(v)  # device arrays → host in one copy
                vals.extend(list(v))
            data[name] = vals
        return pd.DataFrame(data)

    # -- structural transforms ---------------------------------------------
    def select(self, names: Sequence[str]) -> "TensorFrame":
        schema = self.schema.select(names)
        if self.is_materialized:
            blocks = [{n: b[n] for n in names} for b in self._blocks]
            return TensorFrame(blocks, schema)
        from .plan import ir as _plan_ir

        if _plan_ir.fusion_enabled():
            # pending frame: record the projection on the logical plan —
            # pushdown then prunes dead upstream outputs (and whole
            # stages) so dropped columns are never computed, gathered,
            # or transferred (plan/rules.py)
            node = _plan_ir.PlanNode(
                "select",
                parent=_plan_ir.node_for_parent(self),
                names=list(names),
                schema=schema,
            )

            def pending():
                from .plan.lower import execute_plan

                return execute_plan(node)

            out = TensorFrame(None, schema, pending=pending)
            node.bind(out)
            out._plan = node
            return out
        parent = self
        return TensorFrame(
            None, schema, pending=lambda: [{n: b[n] for n in names} for b in parent.blocks()]
        )

    def filter(self, predicate) -> "TensorFrame":
        """Keep the rows where ``predicate`` is true.

        ``predicate`` is a program like any verb's — a python function
        over block columns (parameter names select columns), DSL nodes,
        or a Program — producing ONE boolean output of shape ``[rows]``.
        The mask computes on device through ``map_blocks``; rows subset
        per block — device columns gather IN HBM (only the
        byte-per-row mask crosses to host to fix the data-dependent
        output size), host columns compress. Lazy like the verbs: the
        mask computes when the frame is forced. The reference had no
        filter — Spark's ``where`` ran before tensorframes saw the
        data; standalone frames need it native. A sharded frame's
        result columns stay on device but lose their mesh layout
        (row-dropping is data-dependent — call ``.to_device()`` to
        re-shard). MULTI-PROCESS frames filter process-locally: every
        process keeps its own passing rows (no collective involved),
        yielding a process-local frame like the broadcast join's
        output.
        """
        from .ops.verbs import map_blocks

        masked = map_blocks(predicate, self)
        out_names = [
            c.name for c in masked.schema if c.name not in self.schema.names
        ]
        if len(out_names) != 1:
            raise ValueError(
                f"filter predicate must produce exactly one output; got "
                f"{out_names}"
            )
        mname = out_names[0]
        schema = self.schema
        names = list(schema.names)
        parent = self

        if (
            getattr(masked, "_plan", None) is not None
            and not self.is_sharded
        ):
            import jax as _jax

            from .plan import ir as _plan_ir

            if _jax.process_count() == 1:
                # single-process device-evaluable predicate: the mask
                # program fuses into the upstream run (one dispatch
                # computes upstream outputs AND the mask); the row
                # subsetting itself splits the plan — its output row
                # count is data-dependent. Multi-process and sharded
                # frames keep the explicit paths below.
                node = _plan_ir.PlanNode(
                    "filter",
                    parent=masked._plan,
                    mask_name=mname,
                    schema=schema,
                )

                def plan_pending():
                    from .plan.lower import execute_plan

                    return execute_plan(node)

                out = TensorFrame(None, schema, pending=plan_pending)
                node.bind(out)
                out._plan = node
                return out

        def compute() -> List[Block]:
            new_blocks: List[Block] = []
            for b in masked.blocks():
                mv = b[mname]
                if _non_addressable(mv):
                    # MULTI-PROCESS: every process keeps ITS OWN rows
                    # that pass — the mask's local shard selects from
                    # each column's local shard, purely process-local
                    # (no collective, so no deadlock shape exists), and
                    # the result is a process-local host/device frame
                    # like the broadcast join's output.
                    from .ops.device_agg import extract_local_rows

                    m_loc = extract_local_rows(mv)
                    if m_loc is None:
                        raise RuntimeError(
                            "filter: no addressable shard of the mask "
                            "on this process — re-shard so every "
                            "process holds rows "
                            "(frame_from_process_local)"
                        )
                    m_loc = np.asarray(m_loc)
                    if m_loc.dtype != np.bool_ or m_loc.ndim != 1:
                        raise ValueError(
                            f"filter predicate output {mname!r} must be "
                            f"bool[rows]; got {m_loc.dtype} with shape "
                            f"{m_loc.shape}"
                        )
                    nb: Block = {}
                    for name in names:
                        v_loc = extract_local_rows(b[name])
                        if v_loc is None:
                            raise RuntimeError(
                                f"filter: column {name!r} has no "
                                "addressable shard on this process"
                            )
                        if len(v_loc) != m_loc.shape[0]:
                            # same fail-LOUDLY contract as the
                            # single-process row-count guard below
                            raise ValueError(
                                f"filter predicate output {mname!r} has "
                                f"{m_loc.shape[0]} rows for this "
                                f"process's {len(v_loc)} rows of "
                                f"{name!r}"
                            )
                        if isinstance(b[name], list):
                            nb[name] = [
                                x for x, keep in zip(b[name], m_loc)
                                if keep
                            ]
                        else:
                            nb[name] = np.asarray(v_loc)[m_loc]
                    new_blocks.append(nb)
                    continue
                # single-process subsetting (bool[rows] validation, loud
                # row-count mismatch, device columns gathered in HBM)
                # lives in ONE place, shared with the plan lowering's
                # fused filter — the two paths must never diverge
                from .plan.lower import _apply_mask

                new_blocks.append(_apply_mask(b, names, mname))
            return new_blocks

        # lazy like every sibling transform: the mask + gather run when
        # blocks()/collect() force the frame, so chained verbs keep
        # their one-materialization contract
        return TensorFrame(
            None, schema,
            pending=_spanned("filter", compute, lambda: parent.num_rows),
        )

    def sort_values(self, by, ascending: bool = True) -> "TensorFrame":
        """Rows ordered by one or more key columns (stable: ties keep
        their input order, ascending OR descending; multiple keys sort
        lexicographically, first key primary). Global across blocks —
        the result is one block, like ``repartition(1)``. Another
        affordance the reference left to Spark (``orderBy``). Lazy.

        MULTI-PROCESS frames under ``config.relational_broadcast_bytes``
        allgather their rows in process order (the global row order, so
        ties stay stable) and every process holds the same replicated
        sorted frame. LARGER frames take the range-partitioned exchange
        (``ops/exchange.py`` ≙ Spark's rangepartitioning exchange for
        orderBy): process p receives and sorts the p-th key range, so
        each process holds O(global/P) rows and concatenating the
        per-process results in process order is the global sort order —
        tie stability included (the exchange preserves (process, local
        row) order and the local sort is stable).

        DEVICE frames sort ON DEVICE: when every column is a device
        array and every key is numeric/bool, ordering runs as
        ``jnp.lexsort`` (``lax.sort``) over monotone integer key codes
        and the gather stays in HBM — a large device frame never
        serializes through host memory (VERDICT r3 #7). Object/string
        keys and host columns take the host codes path.
        """
        keys = [by] if isinstance(by, str) else list(by)
        for k in keys:
            self.schema[k]  # unknown column: raise now, not at force
        if isinstance(ascending, bool):
            asc = [ascending] * len(keys)
        else:  # pandas-style per-key list
            asc = [bool(a) for a in ascending]
            if len(asc) != len(keys):
                raise ValueError(
                    f"ascending has {len(asc)} entries for {len(keys)} "
                    "sort keys"
                )
        schema = self.schema
        names = list(schema.names)
        parent = self

        def compute() -> List[Block]:
            import jax

            from .ops.keys import _unique_inverse

            merged = None
            spans = (
                jax.process_count() > 1 and parent.is_sharded
            ) or any(
                _non_addressable(v)
                for b in parent.blocks()
                for v in b.values()
            )
            if spans:
                # MULTI-PROCESS: small frames allgather and sort the
                # replicated union (repartition(1) semantics, every
                # process holds the same block); frames over the
                # broadcast budget take the RANGE EXCHANGE — process p
                # receives only the p-th key range (O(global/P) memory)
                # and sorts it locally (VERDICT r4 #2).
                from .config import get_config
                from .ops import exchange as xch
                from .ops.device_agg import _allgather_dicts

                local = _gathered_local_or_raise(
                    parent, names, "sort_values"
                )
                cfg = get_config()
                # global-bytes estimate is an allgather itself, so every
                # process computes the same number and takes the same
                # branch — no collective divergence
                gbytes = xch.global_frame_bytes(local)
                if gbytes > cfg.relational_broadcast_bytes:
                    if not cfg.relational_exchange:
                        raise RuntimeError(
                            f"sort_values: replicating {gbytes:,} bytes "
                            "on every process exceeds "
                            "config.relational_broadcast_bytes "
                            f"({cfg.relational_broadcast_bytes:,}) and "
                            "the exchange path is disabled "
                            "(config.relational_exchange=False / "
                            "TFTPU_RELATIONAL_EXCHANGE=0) — raise the "
                            "budget, re-enable the exchange, or sort a "
                            "projected/filtered frame"
                        )
                    # layout-switch tripwire (ADVICE r5): the result
                    # contract changes here, once, visibly
                    _warn_sort_layout_switch(
                        gbytes, cfg.relational_broadcast_bytes
                    )
                    t_x = time.perf_counter()
                    part = xch.partition_by_range(
                        [local[k] for k in keys],
                        jax.process_count(),
                        asc,
                    )
                    recv = xch.exchange_rows(local, part)
                    # plan visibility in report(): rows RECEIVED here
                    # (the replicated plan records no such span)
                    profiling.record(
                        "sort_values.exchange",
                        time.perf_counter() - t_x,
                        _block_num_rows(recv),
                    )
                    merged = recv  # this process's key range only
                else:
                    union, _ = _allgather_dicts(
                        [local[n] for n in names]
                    )
                    merged = {
                        name: (
                            list(v)
                            if isinstance(v, np.ndarray)
                            and v.dtype == object
                            else v
                        )
                        for name, v in zip(names, union)
                    }
            if merged is None:
                merged = _merged_global_columns(
                    parent, names, "sort_values", keep_device=True
                )
            # DEVICE path (VERDICT r3 #7): every selected column is a
            # device array and every key is numeric/bool — order and
            # gather entirely on device (jnp.lexsort → lax.sort), so a
            # large device frame never serializes through host memory.
            # Object/string/uint64 keys and host columns take the host
            # codes path below. (The multi-process union is host numpy,
            # so it takes the host path.)
            import jax.numpy as jnp

            def _dev_key_ok(v):
                if not (_is_jax_array(v) and v.ndim == 1):
                    return False
                if jnp.issubdtype(v.dtype, jnp.unsignedinteger):
                    # unsigned keys widen to a signed code: uint8/16
                    # always fit int32; uint32 needs int64, which only
                    # exists with x64 on (astype(int64) silently
                    # canonicalizes to int32 otherwise — 3e9 would wrap
                    # negative and sort first); uint64 cannot widen
                    import jax as _jax

                    if v.dtype.itemsize <= 2:
                        return True
                    return (
                        v.dtype.itemsize == 4
                        and bool(_jax.config.jax_enable_x64)
                    )
                return (
                    v.dtype == jnp.bool_
                    or jnp.issubdtype(v.dtype, jnp.integer)
                    or jnp.issubdtype(v.dtype, jnp.floating)
                )

            if all(_dev_key_ok(merged[k]) for k in keys) and all(
                _is_jax_array(v) for v in merged.values()
            ):
                dev_keys = tuple(
                    _device_sort_codes(merged[k], k_asc)
                    for k, k_asc in zip(reversed(keys), reversed(asc))
                )
                order = jnp.lexsort(dev_keys)
                return [{name: merged[name][order] for name in names}]
            # host path: np.asarray any device columns back first
            merged = {
                name: (np.asarray(v) if _is_jax_array(v) else v)
                for name, v in merged.items()
            }
            key_arrs = []
            # lexsort: LAST key is primary, so iterate reversed
            for k, k_asc in zip(reversed(keys), reversed(asc)):
                v = merged[k]
                arr = (
                    np.asarray(v, dtype=object)
                    if isinstance(v, list) else np.asarray(v)
                )
                if arr.ndim > 1:
                    raise ValueError(
                        f"sort_values: key column {k!r} has non-scalar "
                        f"cells (shape {arr.shape[1:]}); sort keys must "
                        "be scalar columns"
                    )
                # dense integer codes keep DESCENDING sorts stable:
                # negating codes (ints always negate; strings don't)
                # sorts descending while lexsort's stability preserves
                # tie order — order[::-1] would reverse ties.  Encoding
                # rides ops/keys (same as join/aggregate) so mixed-type
                # object keys and NaN floats order deterministically
                # instead of raising from numpy's '<'
                codes = _unique_inverse(arr)[1]
                key_arrs.append(codes if k_asc else -codes)
            order = np.lexsort(key_arrs)
            out: Block = {}
            for name in names:
                v = merged[name]
                if isinstance(v, list):
                    out[name] = [v[i] for i in order]
                else:
                    out[name] = v[order]
            return [out]

        return TensorFrame(
            None, schema,
            pending=_spanned(
                "sort_values", compute, lambda: parent.num_rows
            ),
        )

    def limit(self, n: int) -> "TensorFrame":
        """The first ``n`` rows, as a frame (``take`` returns rows).
        Lazy; forcing materializes the parent's blocks (verbs are
        all-blocks lazy thunks) but only the first ``n`` rows transfer
        or copy.
        """
        if n < 0:
            raise ValueError(f"limit must be >= 0, got {n}")
        schema = self.schema
        names = list(schema.names)
        parent = self

        def compute() -> List[Block]:
            remaining = n
            out_blocks: List[Block] = []
            for b in parent.blocks():
                if remaining <= 0:
                    break
                rows = _block_num_rows(b)
                take_n = min(rows, remaining)
                nb: Block = {}
                for name in names:
                    v = b[name]
                    if _non_addressable(v):
                        raise RuntimeError(
                            "limit: columns span processes — one process "
                            "cannot materialize the global head. Limit "
                            "before frame_from_process_local."
                        )
                    # slice BEFORE np.asarray: device columns then move
                    # only the kept rows host-ward, not the whole block
                    nb[name] = (
                        v[:take_n] if isinstance(v, list)
                        else np.asarray(v[:take_n])
                    )
                out_blocks.append(nb)
                remaining -= take_n
            if not out_blocks:
                for b in parent.blocks()[:1]:
                    nb = {}
                    for name in names:
                        v = b[name]
                        if _non_addressable(v):
                            raise RuntimeError(
                                "limit: columns span processes — one "
                                "process cannot materialize the global "
                                "head. Limit before "
                                "frame_from_process_local."
                            )
                        nb[name] = (
                            [] if isinstance(v, list) else np.asarray(v[:0])
                        )
                    out_blocks.append(nb)
            return out_blocks

        return TensorFrame(None, schema, pending=compute)

    def join(
        self,
        other: "TensorFrame",
        on,
        how: str = "inner",
        suffixes: Tuple[str, str] = ("_x", "_y"),
        fill_value=None,
    ) -> "TensorFrame":
        """Hash join on one or more key columns (the last Spark
        affordance a standalone frame needs). Key encoding rides the
        aggregate machinery (``ops/keys.py``: native hash dictionary for
        strings, O(n) dense codes for ints) so any key type joins; the
        match expansion is fully vectorized (no per-key python loop).
        Result ordering is pandas-like: left-row order, ties in the
        right frame's stable order. Non-key columns sharing a name take
        ``suffixes``.

        ``how="left"`` keeps unmatched left rows; their right-side
        columns take ``fill_value`` (a scalar, or a dict keyed by the
        right column's ORIGINAL name) — explicit fills instead of NaN,
        because NaN would silently retype integer columns.
        ``how="right"`` mirrors it (unmatched RIGHT rows kept, LEFT
        columns filled, pandas-like right-row ordering).
        ``how="outer"`` keeps both: matched + unmatched-left rows in
        left order first, then unmatched right rows in right order
        (pandas sort=False convention); ``fill_value`` must cover the
        non-key columns of BOTH sides. Lazy; returns one block.

        MULTI-PROCESS frames join via a broadcast hash join (VERDICT
        r3 #7) when the right side fits
        ``config.relational_broadcast_bytes``: every process allgathers
        the full RIGHT side (put the smaller frame on the right) and
        joins its own process-local left rows, so no process ever
        materializes the global left. A LARGER right side switches to
        the hash-partitioned exchange (``ops/exchange.py`` ≙ Catalyst's
        shuffle exchange, DebugRowOps.scala:583): both sides
        hash-partition on the key columns over the process axis and
        each process joins one partition — O(global/P) memory, no
        replication. Either way the result is a process-local host
        frame — each process holds its share of the join, like a Spark
        partition's share. Exercised at 2 and 4 real OS processes in
        ``tests/test_distributed.py``.
        """
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(
                f"join supports how='inner'/'left'/'right'/'outer' "
                f"(got {how!r})"
            )
        if how == "right":
            # mirror of the left join with the sides (and suffix roles)
            # swapped; select() restores the canonical keys + left +
            # right column order. Unmatched-right rows keep pandas'
            # right-row ordering because they ARE the swapped call's
            # left rows. fill_value is validated HERE, before the
            # delegation, so errors name how='right' and THIS frame's
            # (the left side's) columns — the swapped call's messages
            # would blame how='left' and swap the frames (ADVICE r5).
            if fill_value is None:
                raise ValueError(
                    "how='right' needs fill_value (scalar or "
                    "{column: value}) for unmatched rows' LEFT-side "
                    "columns — explicit fills instead of NaN, which "
                    "would retype integer columns"
                )
            if isinstance(fill_value, dict):
                ks_r = [on] if isinstance(on, str) else list(on)
                left_need = [
                    c for c in self.schema.names if c not in ks_r
                ]
                missing_r = [c for c in left_need if c not in fill_value]
                if missing_r:
                    raise ValueError(
                        f"how='right': fill_value has no entry for "
                        f"LEFT-side column(s) {missing_r} (unmatched "
                        "right rows fill the left frame's columns)"
                    )
            swapped = other.join(
                self,
                on=on,
                how="left",
                suffixes=(suffixes[1], suffixes[0]),
                fill_value=fill_value,
            )
            ks = [on] if isinstance(on, str) else list(on)
            l_only = [c for c in self.schema.names if c not in ks]
            r_only = [c for c in other.schema.names if c not in ks]
            clash = set(l_only) & set(r_only)
            ordered = (
                ks
                + [c + suffixes[0] if c in clash else c for c in l_only]
                + [c + suffixes[1] if c in clash else c for c in r_only]
            )
            return swapped.select(ordered)
        if how in ("left", "outer") and fill_value is None:
            raise ValueError(
                f"how={how!r} needs fill_value (scalar or "
                "{column: value}) for unmatched rows' columns — "
                "explicit fills instead of NaN, which would retype "
                "integer columns"
            )

        keys = [on] if isinstance(on, str) else list(on)
        for k in keys:
            self.schema[k]
            other.schema[k]
        left_only = [c for c in self.schema.names if c not in keys]
        right_only = [c for c in other.schema.names if c not in keys]
        clashes = set(left_only) & set(right_only)
        lname = {
            c: (c + suffixes[0] if c in clashes else c) for c in left_only
        }
        rname = {
            c: (c + suffixes[1] if c in clashes else c) for c in right_only
        }
        if how in ("left", "outer") and isinstance(fill_value, dict):
            need = list(right_only)
            if how == "outer":  # unmatched RIGHT rows fill left columns
                need += left_only
            missing_fills = [c for c in need if c not in fill_value]
            if missing_fills:
                raise ValueError(
                    f"how={how!r}: fill_value has no entry for "
                    f"column(s) {missing_fills}"
                )
        cols = (
            [self.schema[k] for k in keys]
            + [self.schema[c].with_name(lname[c]) for c in left_only]
            + [other.schema[c].with_name(rname[c]) for c in right_only]
        )
        schema = Schema(cols)
        left, right = self, other
        spec = _JoinSpec(
            keys=tuple(keys),
            how=how,
            lname=tuple((c, lname[c]) for c in left_only),
            rname=tuple((c, rname[c]) for c in right_only),
            fill_value=fill_value,
        )
        if how in ("left", "outer"):
            # fill representability is validated EAGERLY for every
            # fillable device column — the plan's pushdown may prune a
            # column before the join core's per-column check would see
            # it, and a lossy fill must fail identically whether or not
            # the column survives pruning (fused == TFTPU_FUSION=0)
            need_fill = [(c, other.schema[c]) for c in right_only]
            if how == "outer":
                need_fill += [(c, self.schema[c]) for c in left_only]
            for c, info in need_fill:
                if info.is_device and info.dtype.np_dtype is not None:
                    spec.checked_fill(c, np.dtype(info.dtype.np_dtype))

        from .plan import ir as _plan_ir

        if (
            _plan_ir.fusion_enabled()
            and not left.is_sharded
            and not right.is_sharded
        ):
            import jax as _jax

            if _jax.process_count() == 1:
                # single-process hash join ENTERS the plan: upstream
                # probe-side maps fuse into the probe dispatch, and the
                # needed-columns pass prunes through the join on both
                # sides (a downstream select/aggregate that never reads
                # a column keeps it from being computed, gathered, or
                # match-expanded). Multi-process and sharded frames
                # keep the explicit broadcast/exchange paths below.
                node = _plan_ir.PlanNode(
                    "join",
                    parent=_plan_ir.node_for_parent(self),
                    right=other,
                    spec=spec,
                    schema=schema,
                )

                def plan_pending():
                    from .plan.lower import execute_plan

                    return execute_plan(node)

                out = TensorFrame(None, schema, pending=plan_pending)
                node.bind(out)
                out._plan = node
                return out

        def compute() -> List[Block]:
            import jax

            spans = (
                jax.process_count() > 1
                and (left.is_sharded or right.is_sharded)
            ) or any(
                _non_addressable(v)
                for fr in (left, right)
                for b in fr.blocks()
                for v in b.values()
            )
            if spans:
                # Distributed BROADCAST hash join (VERDICT r3 #7,
                # replacing the spans-processes raise): every process
                # allgathers the full RIGHT side (the build side — put
                # the smaller frame on the right), then joins its own
                # LOCAL left rows against it. The result is a
                # process-local host frame — each process holds the
                # join of its left rows, the way a Spark partition
                # holds its share of a broadcast join's output.
                # All processes take this branch deterministically
                # (spans is a property of the global frame), so the
                # allgather collective cannot deadlock.
                from .ops.device_agg import (
                    _allgather_dicts, gather_local_columns, uniform_ok,
                )

                from .config import get_config
                from .ops import exchange as xch

                lcols = gather_local_columns(left, left.schema.names)
                r_names = list(right.schema.names)
                r_local = gather_local_columns(right, r_names)
                if not uniform_ok(
                    lcols is not None and r_local is not None
                ):
                    raise RuntimeError(
                        "join: some process holds no addressable shard "
                        "of a column — re-shard so every process holds "
                        "rows of both sides (frame_from_process_local)"
                    )
                cfg = get_config()
                # allgathered estimate: identical on every process, so
                # the broadcast-vs-exchange branch is uniform. OUTER
                # joins always exchange: under a broadcast plan every
                # process would re-emit right rows its local left
                # happens not to match, duplicating them fleet-wide.
                r_bytes = xch.global_frame_bytes(r_local)
                if (
                    r_bytes > cfg.relational_broadcast_bytes
                    or how == "outer"
                ):
                    if not cfg.relational_exchange:
                        raise RuntimeError(
                            f"join: broadcasting the {r_bytes:,}-byte "
                            "right side to every process "
                            + (
                                "cannot implement an outer join "
                                "(unmatched right rows would duplicate "
                                "per process)"
                                if how == "outer"
                                else "exceeds config."
                                "relational_broadcast_bytes "
                                f"({cfg.relational_broadcast_bytes:,})"
                            )
                            + " and the exchange path is disabled "
                            "(config.relational_exchange=False / "
                            "TFTPU_RELATIONAL_EXCHANGE=0) — raise the "
                            "budget, re-enable the exchange, or put "
                            "the smaller frame on the right"
                        )
                    # SHUFFLE JOIN: both sides hash-partition on the
                    # key columns (content hashes — identical on every
                    # process for identical values) and each process
                    # joins one partition
                    procs = jax.process_count()
                    t_x = time.perf_counter()
                    lpart = xch.partition_by_hash(
                        [lcols[k] for k in keys], procs
                    )
                    rpart = xch.partition_by_hash(
                        [r_local[k] for k in keys], procs
                    )
                    lrecv = xch.exchange_rows(lcols, lpart)
                    rrecv = xch.exchange_rows(r_local, rpart)
                    profiling.record(
                        "join.exchange",
                        time.perf_counter() - t_x,
                        _block_num_rows(lrecv) + _block_num_rows(rrecv),
                    )
                    out = _hash_join_cols(lrecv, rrecv, spec)
                else:
                    union, _ = _allgather_dicts(
                        [r_local[n] for n in r_names]
                    )
                    rcols = dict(zip(r_names, union))
                    out = _hash_join_cols(lcols, rcols, spec)
                for name in list(out):
                    v = out[name]
                    if isinstance(v, np.ndarray) and v.dtype == object:
                        out[name] = list(v)  # host columns store as lists
                return [out]
            lcols = _merged_global_columns(left, left.schema.names, "join")
            rcols = _merged_global_columns(
                right, right.schema.names, "join"
            )
            return [_hash_join_cols(lcols, rcols, spec)]

        return TensorFrame(
            None, schema,
            pending=_spanned(
                "join", compute,
                lambda: left.num_rows + right.num_rows,
            ),
        )

    def drop_duplicates(self, subset=None) -> "TensorFrame":
        """Rows with duplicate keys removed, FIRST occurrence kept in
        global row order (pandas ``drop_duplicates(keep="first")`` /
        Spark ``dropDuplicates``). ``subset`` names the key columns
        (default: every column); keys must be scalar columns, the same
        constraint as sort keys, and every key type the aggregate
        encoder handles works (ints, floats — NaNs compare EQUAL, the
        grouping convention — strings, mixed objects). Lazy; returns
        one block.

        In MULTI-PROCESS programs, frames whose local columns are ALL
        byte-identical on every process (a replicated frame, checked by
        a full-content blake2b allgather) dedup LOCALLY: replicated in,
        replicated out — matching how sort_values/filter/group_by
        treat non-spanning frames (ADVICE r5). Every other layout
        (sharded, or process-local frames whose rows differ) takes the
        hash exchange: duplicates COLOCATE under the content hash, so
        each process's local dedup of its partition is the global
        dedup, regardless of which process originally held which row —
        each process keeps its partition's survivors (process-local
        result, like join). The exchange preserves (process, local
        row) order, so keep-first still follows global row order."""
        keys = (
            list(self.schema.names)
            if subset is None
            else ([subset] if isinstance(subset, str) else list(subset))
        )
        for k in keys:
            self.schema[k]
        schema = self.schema
        names = list(schema.names)
        parent = self

        def compute() -> List[Block]:
            import jax

            from .ops.keys import group_ids

            # multi-process: REPLICATED frames (identical columns
            # fleet-wide, proven by the blake2b allgather — a uniform
            # collective, so every process takes the same branch) dedup
            # locally, keeping replicated-in → replicated-out like
            # sort_values/filter/group_by (ADVICE r5). Everything else
            # exchanges: a process-local frame deduped on a key OTHER
            # than its partition key would silently keep cross-process
            # duplicates on the local path (code-review r5).
            if jax.process_count() > 1:
                from .ops import exchange as xch

                local = _gathered_local_or_raise(
                    parent, names, "drop_duplicates"
                )
                # a SHARDED frame is never replicated, whatever its
                # bytes say: its global frame is the concatenation of
                # the shards, so byte-identical shards (symmetric seed
                # data) still need the exchange to collapse to ONE
                # global survivor — the layout check is uniform
                # fleet-wide, so every process takes the same branch
                if not parent.is_sharded and _replicated_fleetwide(local):
                    logger.debug(
                        "drop_duplicates: every process holds "
                        "identical local columns — deduping locally "
                        "(replicated in, replicated out)"
                    )
                    cols = local
                else:
                    part = xch.partition_by_hash(
                        [local[k] for k in keys], jax.process_count()
                    )
                    cols = xch.exchange_rows(local, part)
            else:
                cols = _merged_global_columns(
                    parent, names, "drop_duplicates"
                )
            key_arrs = []
            for k in keys:
                v = cols[k]
                arr = (
                    np.asarray(v, dtype=object)
                    if isinstance(v, list)
                    else np.asarray(v)
                )
                if arr.ndim > 1:
                    raise ValueError(
                        f"drop_duplicates: key column {k!r} has "
                        f"non-scalar cells (shape {arr.shape[1:]}); "
                        "pass subset= naming scalar columns"
                    )
                key_arrs.append(arr)
            if len(key_arrs[0]) == 0:
                return [dict(cols)]
            codes, _, _ = group_ids(key_arrs)
            # first occurrence per group, back in original row order
            keep = np.sort(np.unique(codes, return_index=True)[1])
            out: Block = {}
            for name in names:
                v = cols[name]
                if isinstance(v, list):
                    out[name] = [v[i] for i in keep]
                else:
                    out[name] = v[keep]
            return [out]

        return TensorFrame(
            None, schema,
            pending=_spanned(
                "drop_duplicates", compute, lambda: parent.num_rows
            ),
        )

    def distinct(self) -> "TensorFrame":
        """Spark-name alias for :meth:`drop_duplicates` over every
        column."""
        return self.drop_duplicates()

    def repartition_by_key(self, on) -> "TensorFrame":
        """Hash-partition rows by key across the process fleet (≙ Spark's
        ``repartition(col)`` exchange): afterwards every row whose key
        hashes to process p lives ON process p, as a process-local host
        frame. Frames repartitioned on the same key are CO-PARTITIONED —
        joining or aggregating them afterwards runs process-locally (the
        join's ``spans`` test sees plain local frames), with no further
        collectives: pay the shuffle once, reuse it across a pipeline.
        The partitioner is ``ops.exchange.partition_by_hash`` — the same
        content-stable hash the over-budget shuffle join uses, so a
        repartitioned frame joins consistently with exchange-planned
        ones. EAGER (the exchange runs now, not at force time);
        single-process frames return themselves unchanged. The result's
        ``num_rows`` is the LOCAL partition's row count, like every
        process-local frame.

        The global frame is taken to be the UNION of the processes'
        local rows (the contract of every process-local frame). Do NOT
        call this on a REPLICATED frame — e.g. an under-budget
        multi-process ``sort_values`` result, where every process
        holds the full global frame — or each row arrives P times; a
        warning fires when the local rows look identical fleet-wide."""
        keys = [on] if isinstance(on, str) else list(on)
        for k in keys:
            self.schema[k]
        import jax

        if jax.process_count() == 1:
            return self
        from .ops import exchange as xch

        names = list(self.schema.names)
        local = _gathered_local_or_raise(
            self, names, "repartition_by_key"
        )
        # replication tripwire: checksum a bounded key sample and
        # compare fleet-wide. Identical partitions CAN be legitimate
        # (then P-fold multiplicity is the correct union semantics), so
        # this warns rather than raises.
        import zlib

        from jax.experimental import multihost_utils as _mh

        probe = xch.content_hash64([local[k] for k in keys])[:1024]
        crc = zlib.crc32(probe.tobytes()) if len(probe) else 0
        crcs = np.asarray(
            _mh.process_allgather(np.asarray([crc], np.int64))
        ).reshape(-1)
        if len(probe) and len(set(crcs.tolist())) == 1:
            logger.warning(
                "repartition_by_key: every process holds identical-"
                "looking local rows — if this frame is REPLICATED "
                "(e.g. an under-budget multi-process sort_values "
                "result), the exchange will duplicate each row "
                "process_count times; repartition the original "
                "sharded frame instead"
            )
        t_x = time.perf_counter()
        part = xch.partition_by_hash(
            [local[k] for k in keys], jax.process_count()
        )
        recv = xch.exchange_rows(local, part)
        profiling.record(
            "repartition_by_key", time.perf_counter() - t_x,
            _block_num_rows(recv),
        )
        return TensorFrame([{n: recv[n] for n in names}], self.schema)

    def with_column_renamed(self, old: str, new: str) -> "TensorFrame":
        schema = Schema(
            [c.with_name(new) if c.name == old else c for c in self.schema]
        )
        parent = self
        return TensorFrame(
            None,
            schema,
            pending=lambda: [
                {(new if k == old else k): v for k, v in b.items()}
                for b in parent.blocks()
            ],
        )

    def alias_column(self, name: str, alias: str) -> "TensorFrame":
        """Duplicate a column under a new name (≙ ``df.select(y, y.alias("z"))``
        in the README reduce example, README.md:114)."""
        schema = self.schema.append([self.schema[name].with_name(alias)])
        parent = self
        return TensorFrame(
            None,
            schema,
            pending=lambda: [dict(b, **{alias: b[name]}) for b in parent.blocks()],
        )

    def repartition(self, num_blocks: int) -> "TensorFrame":
        """Re-chunk rows into ``num_blocks`` roughly equal blocks."""
        blocks = self.blocks()
        merged: Dict[str, Union[np.ndarray, list]] = {}
        for name in self.schema.names:
            vals = []
            dense = True
            for b in blocks:
                v = b[name]
                if isinstance(v, list):
                    dense = False
                    vals.extend(v)
                else:
                    vals.append(v)
            if dense:
                merged[name] = (
                    np.concatenate(vals, axis=0)
                    if vals
                    else np.empty((0,), dtype=self.schema[name].dtype.np_dtype)
                )
            else:
                flat = []
                for v in vals:
                    flat.append(v)
                merged[name] = flat
        total = len(next(iter(merged.values()))) if merged else 0
        bounds = _partition_bounds(total, num_blocks)
        out_blocks = []
        for lo, hi in bounds:
            out_blocks.append({k: v[lo:hi] for k, v in merged.items()})
        out = TensorFrame(out_blocks, self.schema)
        from .plan import ir as _plan_ir

        _plan_ir.mark_barrier(out, "repartition materialization", self)
        return out

    def cache(self) -> "TensorFrame":
        self.blocks()
        return self

    def save(self, path: str) -> "TensorFrame":
        """Persist to ``path`` (see ``io.save_frame``); returns self."""
        from .io import save_frame

        save_frame(self, path)
        return self

    # -- device placement ---------------------------------------------------
    @property
    def is_sharded(self) -> bool:
        """True when column storage is global ``jax.Array``\\ s over a mesh."""
        return getattr(self, "_mesh", None) is not None

    @property
    def mesh(self):
        return getattr(self, "_mesh", None)

    def to_device(self, mesh=None, axis: Optional[str] = None) -> "TensorFrame":
        """Shard the frame over a device mesh: every device column becomes a
        single global ``jax.Array`` with its row dim split over the batch
        axis (≙ a Spark DataFrame's partitions living on executors — but in
        HBM, and chained map verbs never leave the device).

        Host-only columns stay host-resident and ride along.
        """
        import jax

        from .parallel.mesh import batch_sharding, make_mesh

        mesh = mesh or make_mesh()
        axis = axis or get_config().batch_axis
        dp = mesh.shape[axis]
        blocks = self.blocks()
        total = self.num_rows
        # XLA shards only divisible lead dims; the remainder rows stay in a
        # small host tail block (verbs handle multi-block frames natively),
        # so no padding ever corrupts reduction semantics.
        n_main = (total // dp) * dp
        demote = dt.demotion_active()
        merged: Block = {}
        tail: Block = {}
        infos: List[ColumnInfo] = []
        for info in self.schema:
            parts = [b[info.name] for b in blocks]
            if info.is_device and all(not isinstance(p, list) for p in parts):
                arr = np.concatenate([np.asarray(p) for p in parts], axis=0)
                if demote and dt.demote(info.dtype) is not info.dtype:
                    # x64 demotion: store HBM-resident columns in 32-bit
                    # (halves bandwidth/footprint); schema follows so
                    # downstream validation/analysis see a consistent
                    # 32-bit world
                    info = ColumnInfo(
                        info.name, dt.demote(info.dtype), info.block_shape
                    )
                    arr = arr.astype(info.dtype.np_dtype)
                sharding = batch_sharding(mesh, arr.ndim, axis)
                merged[info.name] = jax.device_put(arr[:n_main], sharding)
                if n_main < total:
                    tail[info.name] = arr[n_main:]
            else:
                flat = []
                for p in parts:
                    flat.extend(list(p))
                merged[info.name] = flat[:n_main]
                if n_main < total:
                    tail[info.name] = flat[n_main:]
            infos.append(info)
        out_blocks = [merged] + ([tail] if n_main < total else [])
        out = TensorFrame(out_blocks, Schema(infos))
        out._mesh = mesh
        out._axis = axis
        return out

    def to_host(self, num_blocks: Optional[int] = None) -> "TensorFrame":
        """Materialize device columns back to host numpy blocks."""
        blocks = self.blocks()
        host_blocks: List[Block] = []
        for b in blocks:
            host_blocks.append(
                {
                    k: (np.asarray(v) if not isinstance(v, list) else v)
                    for k, v in b.items()
                }
            )
        frame = TensorFrame(host_blocks, self.schema)
        if num_blocks:
            frame = frame.repartition(num_blocks)
        from .plan import ir as _plan_ir

        # explicit materialization: downstream chains re-root here
        # (TFG107 names this when fusable maps sit on both sides) —
        # marked AFTER any repartition so the returned frame carries it
        _plan_ir.mark_barrier(
            frame, "to_host/to_numpy materialization", self
        )
        return frame

    # -- verb methods (≙ Implicits.RichDataFrame, dsl/Implicits.scala:25-100:
    # the Scala API pimps DataFrame with the verbs; here they are plain
    # methods delegating to the functional API) ----------------------------

    def map_blocks(self, fetches, feed_dict=None, trim: bool = False,
                   strict: bool = False):
        from .ops.verbs import map_blocks

        return map_blocks(fetches, self, feed_dict=feed_dict, trim=trim,
                          strict=strict)

    def map_blocks_trimmed(self, fetches, feed_dict=None,
                           strict: bool = False):
        """≙ ``mapBlocksTrimmed`` (dsl/Implicits.scala:49-55)."""
        return self.map_blocks(fetches, feed_dict=feed_dict, trim=True,
                               strict=strict)

    def map_rows(self, fetches, feed_dict=None, strict: bool = False):
        from .ops.verbs import map_rows

        return map_rows(fetches, self, feed_dict=feed_dict, strict=strict)

    def reduce_rows(self, fetches, strict: bool = False):
        from .ops.verbs import reduce_rows

        return reduce_rows(fetches, self, strict=strict)

    def reduce_blocks(self, fetches, strict: bool = False):
        from .ops.verbs import reduce_blocks

        return reduce_blocks(fetches, self, strict=strict)

    def analyze(self) -> "TensorFrame":
        """≙ ``RichDataFrame.analyze`` (dsl/Implicits.scala:69-71)."""
        return analyze(self)

    def explain_tensors(self) -> str:
        """≙ ``explainTensors`` (dsl/Implicits.scala:77-79)."""
        return explain(self)

    def explain(self, detailed: bool = False,
                analyze: bool = False) -> str:
        """Schema + tensor metadata rendering; ``detailed=True`` adds
        the physical layout. ``analyze=True`` is EXPLAIN ANALYZE
        (ISSUE 17): appends the plan tree annotated with the per-stage
        profile recorded by the frame's last adaptive execution (see
        :func:`tensorframes_tpu.explain_plan`)."""
        return explain(self, detailed=detailed, analyze=analyze)

    def group_by(self, *keys: str) -> "GroupedData":
        """Group rows by key column(s) for keyed ``aggregate``
        (≙ ``df.groupBy("key")`` feeding ``tfs.aggregate``, core.py:401-419)."""
        for k in keys:
            self.schema[k]  # raises with available columns if missing
        return GroupedData(self, list(keys))


class GroupedData:
    """A frame grouped by key columns (≙ ``RelationalGroupedDataset``;
    the reference reflects the backing frame out of it,
    DebugRowOps.scala:714-737 — here it is just a field)."""

    def __init__(self, frame: "TensorFrame", keys: List[str]):
        self.frame = frame
        self.keys = keys

    def aggregate(self, fetches, strict: bool = False) -> "TensorFrame":
        """≙ ``RichRelationalGroupedDataset.aggregate``
        (dsl/Implicits.scala:107-116)."""
        from .ops.verbs import aggregate

        return aggregate(fetches, self, strict=strict)

    def count(self) -> "TensorFrame":
        """Rows per key (the ``groupBy().count()`` affordance): sums a
        ones column through a DSL reducer fetch so ``segment_reduce_info``
        recognizes it and the segment/device-aggregate fast paths apply
        (a plain-function fetch would take the generic chunked path and
        host-gather on multi-host frames)."""
        import numpy as np_

        from . import dsl
        from .ops.verbs import aggregate

        ones = TensorFrame(
            [
                dict(b, count_tmp=np_.ones(_block_num_rows(b), np_.int64))
                for b in self.frame.blocks()
            ],
            self.frame.schema.append(
                [ColumnInfo("count_tmp", dt.int64, Shape((Unknown,)))]
            ),
        )
        if self.frame.is_sharded:
            ones._mesh = self.frame.mesh
            ones._axis = getattr(self.frame, "_axis", None)
        with dsl.with_graph():
            cnt_in = dsl.block(ones, "count_tmp", tf_name="count_tmp_input")
            cnt = dsl.reduce_sum(cnt_in, axis=0, name="count_tmp")
        out = aggregate(cnt, GroupedData(ones, self.keys))
        return out.with_column_renamed("count_tmp", "count")

    def __repr__(self):
        return f"GroupedData(keys={self.keys}, {self.frame!r})"


def _partition_bounds(total: int, num_blocks: int) -> List[tuple]:
    num_blocks = max(1, num_blocks)
    base = total // num_blocks
    rem = total % num_blocks
    bounds = []
    lo = 0
    for i in range(num_blocks):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


# ---------------------------------------------------------------------------
# Constructors
# ---------------------------------------------------------------------------

def _infer_column_info(name: str, cells: Sequence) -> ColumnInfo:
    """Schema inference from the first cell, mirroring the reference's
    read-or-infer path (ColumnInformation.scala:46-58, :124-138): scalars
    get exact metadata; nested lists get Unknown dims per nesting level
    (the ArrayType recursion prepends Unknown)."""
    if len(cells) == 0:
        raise ValueError(f"Column {name!r} is empty; cannot infer schema")
    first = cells[0]
    depth = _nested_depth(first)
    leaf = _leaf_value(first)
    dtype = dt.from_python_value(leaf)
    if not dtype.device and depth > 0:
        raise dt.UnsupportedTypeError(
            f"Column {name!r}: {dtype.name} columns support scalar cells only"
        )
    cell_shape = Shape.unknown(depth)
    return ColumnInfo(name, dtype, cell_shape.prepend(Unknown))


def _cells_to_storage(cells: Sequence, info: ColumnInfo):
    """Pack cells into dense ndarray storage when possible, else keep a list."""
    if not info.is_device:
        return list(cells)
    if isinstance(cells, np.ndarray):
        return np.ascontiguousarray(cells.astype(info.dtype.np_dtype, copy=False))
    try:
        arr = np.asarray(list(cells))
        if arr.dtype == object:
            return list(cells)
        return np.ascontiguousarray(arr.astype(info.dtype.np_dtype, copy=False))
    except ValueError:
        # ragged — keep as list of cells
        return [np.asarray(c, dtype=info.dtype.np_dtype) if not np.isscalar(c) else c for c in cells]


def frame_from_rows(
    rows: Sequence[Dict[str, object]], num_blocks: Optional[int] = None
) -> TensorFrame:
    """Build a frame from row dicts (≙ ``sqlContext.createDataFrame(data)``
    with ``Row`` objects, README.md:67-68)."""
    if not rows:
        raise ValueError("Cannot build a frame from zero rows without a schema")
    from . import native

    names = list(rows[0].keys())
    num_blocks = num_blocks or min(get_config().default_num_blocks, len(rows))
    cols: Dict[str, object] = {}
    infos: List[ColumnInfo] = []
    use_native = native.available()
    for n in names:
        arr = None
        if use_native:
            # native fast path: scalar numeric columns gather in one C++
            # pass (≙ convertFast0, DataOps.scala:63-81); anything it can't
            # take — vectors, strings, mixed cells — falls back per column
            try:
                dtype = dt.from_python_value(rows[0][n])
            except dt.UnsupportedTypeError:
                dtype = None
            if (
                dtype is not None
                and dtype.device
                and dtype.np_dtype is not None
                and native.supported_dtype(dtype.np_dtype)
                and not isinstance(rows[0][n], (list, tuple, np.ndarray))
            ):
                try:
                    arr = native.gather_column(rows, n, dtype.np_dtype)
                except (TypeError, KeyError, OverflowError, ValueError):
                    arr = None
        if arr is not None:
            cols[n] = arr
            infos.append(ColumnInfo(n, dt.from_numpy(arr.dtype), Shape((Unknown,))))
        else:
            cells = [r[n] for r in rows]
            cols[n] = cells
            infos.append(_infer_column_info(n, cells))
    schema = Schema(infos)
    bounds = _partition_bounds(len(rows), num_blocks)
    blocks: List[Block] = []
    for lo, hi in bounds:
        block: Block = {}
        for info in infos:
            c = cols[info.name]
            if isinstance(c, np.ndarray):
                block[info.name] = c[lo:hi]
            else:
                block[info.name] = _cells_to_storage(c[lo:hi], info)
        blocks.append(block)
    return TensorFrame(blocks, schema)


def frame_from_arrays(
    data: Dict[str, Union[np.ndarray, Sequence]],
    num_blocks: Optional[int] = None,
) -> TensorFrame:
    """Build a frame from column name → array (lead dim = rows). Dense
    arrays get exact cell shapes in the schema immediately (no analyze
    needed — the shape is manifest)."""
    names = list(data.keys())
    if not names:
        raise ValueError("No columns")
    arrays: Dict[str, Union[np.ndarray, list]] = {}
    infos: List[ColumnInfo] = []
    n_rows = None
    for name in names:
        v = data[name]
        if isinstance(v, np.ndarray) and v.dtype != object:
            dtype = dt.from_numpy(v.dtype)
            info = ColumnInfo(name, dtype, Shape(v.shape).with_leading_unknown())
            arrays[name] = np.ascontiguousarray(v)
        else:
            cells = list(v)
            info = _infer_column_info(name, cells)
            stored = _cells_to_storage(cells, info)
            if isinstance(stored, np.ndarray):
                info = info.with_block_shape(
                    Shape(stored.shape).with_leading_unknown()
                )
            arrays[name] = stored
        if n_rows is None:
            n_rows = len(arrays[name])
        elif len(arrays[name]) != n_rows:
            raise ValueError(
                f"Column {name!r} has {len(arrays[name])} rows, expected {n_rows}"
            )
        infos.append(info)
    schema = Schema(infos)
    num_blocks = num_blocks or min(get_config().default_num_blocks, max(n_rows, 1))
    bounds = _partition_bounds(n_rows, num_blocks)
    blocks = [{k: v[lo:hi] for k, v in arrays.items()} for lo, hi in bounds]
    return TensorFrame(blocks, schema)


def frame_from_pandas(pdf, num_blocks: Optional[int] = None) -> TensorFrame:
    """Build a frame from a pandas DataFrame (≙ the reference's pandas debug
    path, core.py:171-183 — here a first-class constructor)."""
    data = {}
    for name in pdf.columns:
        col = pdf[name]
        if col.dtype == object:
            data[name] = list(col)
        else:
            data[name] = col.to_numpy()
    return frame_from_arrays(data, num_blocks=num_blocks)


# ---------------------------------------------------------------------------
# Shape tooling: analyze / append_shape / print_schema
# ---------------------------------------------------------------------------

def _analyze_block_column(cells, info: ColumnInfo) -> Shape:
    """Merged cell shape over one block's cells
    (≙ per-partition loop in deepAnalyzeDataFrame,
    ExperimentalOperations.scala:96-110)."""
    if isinstance(cells, np.ndarray):
        return Shape(cells.shape[1:])
    merged: Optional[Shape] = None
    for c in cells:
        s = shape_of_nested(c)
        if merged is None:
            merged = s
        else:
            m = merged.merge(s)
            if m is None:
                raise ValueError(
                    f"Column {info.name!r}: cells have incompatible ranks "
                    f"({merged} vs {s})"
                )
            merged = m
    if merged is None:  # empty block: no information
        return info.cell_shape
    return merged


def analyze(frame: TensorFrame) -> TensorFrame:
    """Full-scan shape discovery: returns a new frame whose schema carries
    exact cell shapes wherever the data agrees, Unknown where it doesn't.

    ≙ ``tfs.analyze`` (core.py:366-379) →
    ``ExtraOperations.deepAnalyzeDataFrame``
    (ExperimentalOperations.scala:89-132). As in the reference this is a
    full pass over the data; unlike the reference it also *densifies*
    ragged-stored columns that turn out to be uniform, so later verbs take
    the fast dense path.
    """
    blocks = frame.blocks()
    new_infos: List[ColumnInfo] = []
    for info in frame.schema:
        cell_shape: Optional[Shape] = None
        for b in blocks:
            if _block_num_rows(b) == 0:
                continue
            s = _analyze_block_column(b[info.name], info)
            if cell_shape is None:
                cell_shape = s
            else:
                m = cell_shape.merge(s)
                if m is None:
                    raise ValueError(
                        f"Column {info.name!r}: blocks disagree on rank "
                        f"({cell_shape} vs {s})"
                    )
                cell_shape = m
        if cell_shape is None:
            cell_shape = info.cell_shape
        new_infos.append(
            ColumnInfo(info.name, info.dtype, cell_shape.prepend(Unknown))
        )
    new_schema = Schema(new_infos)
    # densify uniform ragged columns
    new_blocks: List[Block] = []
    for b in blocks:
        nb: Block = {}
        for info in new_infos:
            v = b[info.name]
            if (
                isinstance(v, list)
                and info.is_device
                and not info.cell_shape.has_unknown
            ):
                nb[info.name] = np.asarray(v, dtype=info.dtype.np_dtype).reshape(
                    (len(v),) + tuple(info.cell_shape.dims)
                )
            else:
                nb[info.name] = v
        new_blocks.append(nb)
    return TensorFrame(new_blocks, new_schema)


def append_shape(frame: TensorFrame, col: str, shape) -> TensorFrame:
    """Manually declare the cell shape of a column, skipping the analyze
    scan (≙ ``tfs.append_shape``, core.py:381-399;
    ExperimentalOperations.scala:53-68). ``None`` entries mean Unknown.
    The user is responsible for correctness; mismatches surface at
    execution, as in the reference."""
    cell = Shape.from_any(shape)
    info = frame.schema[col]
    new_info = info.with_block_shape(cell.prepend(Unknown))
    parent = frame

    def compute():
        out = []
        for b in parent.blocks():
            v = b[col]
            if isinstance(v, list) and new_info.is_device and not cell.has_unknown:
                v = np.asarray(v, dtype=new_info.dtype.np_dtype).reshape(
                    (len(v),) + tuple(cell.dims)
                )
            out.append(dict(b, **{col: v}))
        return out

    return TensorFrame(None, frame.schema.replace(new_info), pending=compute)


def explain(frame: TensorFrame, detailed: bool = False,
            analyze: bool = False) -> str:
    """Schema rendering with tensor metadata (≙ ``OperationsInterface.explain``,
    DebugRowOps.scala:535-552). With ``detailed=True`` adds the physical
    layout — block row counts, storage kinds, device placement
    (≙ ``explainDetailed``, ExperimentalOperations.scala:26-37) —
    materializing the frame if needed. With ``analyze=True`` appends
    the EXPLAIN ANALYZE view: the plan tree annotated with the
    per-stage profile, decisions, and TFG cross-references recorded by
    the frame's last adaptive execution (ISSUE 17)."""
    base = frame.schema.explain()
    if analyze:
        from .plan import explain_plan as _explain_plan

        base = base + "\n\n" + _explain_plan(frame, analyze=True)
    if not detailed:
        return base
    lines = [base, ""]
    if dt.demotion_active():
        demoting = [
            c.name
            for c in frame.schema.device_columns
            if dt.demote(c.dtype) is not c.dtype
        ]
        lines.append(
            "x64 demotion active (config.demote_x64_on_tpu): "
            "float64->float32, int64->int32 at the device boundary"
            + (f"; affected columns: {demoting}" if demoting else "")
        )
    state = "materialized" if frame.is_materialized else "lazy (forcing)"
    blocks = frame.blocks()
    lines.append(
        f"layout: {len(blocks)} block(s), {frame.num_rows} row(s), "
        f"{'sharded over ' + str(dict(frame.mesh.shape)) if frame.is_sharded else 'host-resident'}"
        f" [{state}]"
    )
    for i, b in enumerate(blocks):
        kinds = []
        for name in frame.schema.names:
            v = b[name]
            if isinstance(v, list):
                kinds.append(f"{name}: list")
            elif isinstance(v, np.ndarray):
                kinds.append(f"{name}: np{list(v.shape)}")
            else:
                spec = getattr(getattr(v, "sharding", None), "spec", None)
                at = f"@{tuple(spec)}" if spec is not None else ""
                kinds.append(
                    f"{name}: device{list(getattr(v, 'shape', []))}{at}"
                )
        lines.append(f"  block {i}: {_block_num_rows(b)} rows  ({', '.join(kinds)})")
    return "\n".join(lines)


def print_schema(frame: TensorFrame) -> None:
    """≙ ``tfs.print_schema`` (core.py:355-364)."""
    print(explain(frame))


@_functools.lru_cache(maxsize=1)
def _describe_stats_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stats(v):
        # per-block (mean, M2, min, max): the two-pass mean/M2 form is
        # cancellation-free even when x64 is disabled and accumulation
        # silently runs in f32 (sum-of-squares would lose everything for
        # |mean| >> std); blocks merge with the Chan parallel-variance
        # recurrence on the host in python floats (always f64)
        v = v.astype(jnp.float64)
        m = v.mean()
        return jnp.stack([m, ((v - m) ** 2).sum(), v.min(), v.max()])

    return stats


def describe(frame: TensorFrame, columns: Optional[Sequence[str]] = None):
    """Summary statistics per scalar numeric column — count, mean, std,
    min, max. One jitted stats program runs per block (on sharded frames
    the block is a global array, so the stats reduce SPMD through
    compiler collectives); the tiny per-block partials merge on the host
    with the parallel-variance recurrence.

    Returns {column: {"count", "mean", "std", "min", "max"}} — the Spark
    ``describe()`` affordance the reference's users had from the host
    DataFrame API. Empty frames report count 0 and NaN moments.
    """
    import jax.numpy as jnp

    if columns is None:
        columns = [
            c.name
            for c in frame.schema.device_columns
            if c.cell_shape.rank == 0
        ]
    else:
        for c in columns:
            info = frame.schema[c]
            if not info.is_device or info.cell_shape.rank != 0:
                raise ValueError(
                    f"describe: column {c!r} is not a scalar numeric column"
                )
    if not columns:
        return {}

    stats = _describe_stats_fn()
    partials: Dict[str, list] = {c: [] for c in columns}
    ns: List[int] = []
    for b in frame.blocks():
        n = _block_num_rows(b)
        if n == 0:
            continue
        ns.append(n)
        for c in columns:
            partials[c].append(np.asarray(stats(jnp.asarray(b[c]))))
    out = {}
    nan = float("nan")
    for c in columns:
        if not ns:
            out[c] = {"count": 0, "mean": nan, "std": nan, "min": nan, "max": nan}
            continue
        # Chan et al. pairwise merge of (n, mean, M2)
        n_t, mean_t, m2_t = 0, 0.0, 0.0
        lo, hi = float("inf"), float("-inf")
        for n_b, p in zip(ns, partials[c]):
            mean_b, m2_b = float(p[0]), float(p[1])
            delta = mean_b - mean_t
            n_new = n_t + n_b
            m2_t = m2_t + m2_b + delta * delta * n_t * n_b / n_new
            mean_t = mean_t + delta * n_b / n_new
            n_t = n_new
            lo, hi = min(lo, float(p[2])), max(hi, float(p[3]))
        out[c] = {
            "count": n_t,
            "mean": mean_t,
            "std": float(np.sqrt(max(m2_t / n_t, 0.0))),
            "min": lo,
            "max": hi,
        }
    return out
