"""Content-keyed persistent tables: cached query results + aggregate
partials (ISSUE 20 / ROADMAP #3).

:class:`BlockStore` segments are keyed by store-local block ids
(``blk-%08d``) that mean nothing across processes, so the registered-
query result cache cannot ride them directly: a second serving process
must find the FIRST process's cached result under nothing but content
keys — (plan fingerprint, input-partition digest) for whole results,
(plan fingerprint, chunk signature) for per-chunk aggregate partials.
:class:`ResultStore` is that mapping: one CRC-framed file per table
under a caller-chosen root (``<TFTPU_COMPILE_CACHE>/results`` in
serving), atomic-rename publish, quarantine-on-corruption — the same
durability discipline as the block and compile stores, minus the
budget/LRU machinery (entries are small aggregate tables, not frame
blocks; eviction is the operator's ``rm -r``).

A *table* here is ``{column name: np.ndarray | list}`` — exactly what
:meth:`TensorFrame.column_values` yields per column. Serialization is
pickle (the established idiom for host columns — store.py's
``host.pkl``), CRC32-framed so a torn write or bit flip NEVER
deserializes into a wrong answer: :meth:`load` reports it as
``corrupt`` and the caller recomputes (counted).

Chunk-arrival manifests (which part files existed, in what order, with
what signatures) live with the scan helpers in :func:`io.part_manifest`;
this module only persists what was computed from them.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

__all__ = ["ResultStore"]

#: File frame: magic + format byte, then little-endian (crc32, length)
#: of the pickled table payload. Bump the magic on layout changes so
#: old entries miss cleanly instead of mis-deserializing.
_MAGIC = b"TFRS\x01"
_HEADER = struct.Struct("<IQ")

_WRITES = _counter(
    "tftpu_resultstore_writes_total",
    "Tables published into content-keyed result stores",
)
_CORRUPT = _counter(
    "tftpu_resultstore_corrupt_total",
    "Result-store entries that failed CRC/format verification on load "
    "and were quarantined (the caller recomputes — corruption never "
    "serves a wrong answer)",
)


_KEY_CHARS = frozenset(
    "0123456789abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ-._"
)


def _safe_key(key: str) -> str:
    if not key or any(c not in _KEY_CHARS for c in key):
        raise ValueError(
            f"result-store keys must be non-empty [alnum.-_] strings, "
            f"got {key!r}"
        )
    return key


class ResultStore:
    """One directory of CRC-framed, content-keyed tables."""

    def __init__(self, root: str):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        return os.path.join(self.root, _safe_key(key) + ".tbl")

    # -- read ---------------------------------------------------------------

    def load(self, key: str) -> Tuple[Optional[Dict[str, object]], bool]:
        """``(table, corrupt)``: the stored table and ``False``; a clean
        miss is ``(None, False)``; a present-but-damaged entry is
        quarantined and reported as ``(None, True)`` so the caller can
        COUNT the recompute it now owes."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None, False
        except OSError as e:  # unreadable counts as damage, not a miss
            logger.warning("result store %s: read failed: %s", key, e)
            self._quarantine(path, f"read failed: {e}")
            return None, True
        try:
            if blob[: len(_MAGIC)] != _MAGIC:
                raise ValueError("bad magic / format version")
            crc, length = _HEADER.unpack_from(blob, len(_MAGIC))
            payload = blob[len(_MAGIC) + _HEADER.size:]
            if len(payload) != length:
                raise ValueError(
                    f"truncated payload ({len(payload)} != {length})"
                )
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError("CRC mismatch")
            table = pickle.loads(payload)
            if not isinstance(table, dict):
                raise ValueError(f"payload is {type(table).__name__}, "
                                 "not a column table")
        except Exception as e:
            logger.warning("result store %s: corrupt entry: %s", key, e)
            self._quarantine(path, str(e))
            return None, True
        return table, False

    # -- write --------------------------------------------------------------

    def put(self, key: str, table: Dict[str, object]) -> int:
        """Publish ``table`` under ``key`` (last-writer-wins, atomic
        rename — a concurrent reader sees the old entry or the new one,
        never a torn file). Returns bytes written."""
        payload = pickle.dumps(dict(table), protocol=4)
        blob = (_MAGIC
                + _HEADER.pack(zlib.crc32(payload) & 0xFFFFFFFF,
                               len(payload))
                + payload)
        path = self._path(key)
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        _WRITES.inc()
        return len(blob)

    def drop(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def keys(self) -> List[str]:
        return sorted(
            name[: -len(".tbl")]
            for name in os.listdir(self.root)
            if name.endswith(".tbl")
        )

    def _quarantine(self, path: str, why: str) -> None:
        _CORRUPT.inc()
        try:
            os.replace(path, path + ".corrupt")
        except OSError:  # racing quarantine/unlink: gone either way
            pass
        logger.warning("result store quarantined %s (%s)", path, why)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ResultStore(root={self.root!r})"
