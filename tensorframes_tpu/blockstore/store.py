"""Spillable columnar block store: the out-of-core data plane's bottom
layer (ROADMAP #3).

A :class:`BlockStore` holds frame blocks (``{column: ndarray | list}``,
the same ``Block`` shape ``TensorFrame`` partitions into) under a
configurable resident-bytes budget (``TFTPU_BLOCK_BUDGET_MB`` /
``configure(block_budget_bytes=)``). Blocks past the budget spill to
disk least-recently-used; spilled segments reload on demand — CRC-checked
by default, or as zero-read ``np.memmap`` views for whole-frame rebuilds
where the OS page cache owns residency.

Durability follows the compile-store contract (compilecache/store.py):

* segments publish via write-temp → fsync → atomic rename, so a crash
  mid-spill can never leave a half-written block under the live name;
* every dense column and the host pickle carry a CRC32 in the
  manifest; a corrupt/truncated reload is **counted**, the segment is
  **quarantined** (renamed aside, never silently re-read), and
  :meth:`BlockStore.get_or_recompute` falls back to recomputing the
  block from its lineage instead of serving bad bytes.

Consumers: ``TensorFrame.spill_to`` (frame.py), the chunked
``read_csv``/``read_parquet`` ingest (io.py), the streaming partitioner
(partitioner.py), the distributed shuffle's per-rank spill files
(shuffle.py), and ``serving.kvpool.PagedKVPool.spill`` (the KV pool's
host-swap tier). Fault site ``blockstore.spill`` (+ delay semantics)
rides the resilience registry; ``blockstore.*`` flight records land in
the crash black box.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
import zlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from ..config import get_config
from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..observability.metrics import gauge as _gauge
from ..observability.metrics import histogram as _histogram
from ..resilience.faults import delay_point, fault_point
from ..utils import get_logger
from ..utils.npz import decode_array, encode_array

logger = get_logger(__name__)

# Data-plane telemetry, pre-registered at import (the blockstore module
# is imported by the package root, so every exposition carries these
# even before the first spill).
RESIDENT_BYTES = _gauge(
    "tftpu_blockstore_resident_bytes",
    "Bytes of block data currently held in host RAM across live block "
    "stores (delta-tracked, like the decode free-pages gauge: several "
    "stores share the one process-wide series)",
)
SPILLED_BYTES = _gauge(
    "tftpu_blockstore_spilled_bytes",
    "Bytes of block data currently spilled to disk segments across "
    "live block stores (delta-tracked)",
)
SPILL_SECONDS = _histogram(
    "tftpu_blockstore_spill_seconds",
    "Wall-clock to publish one block's spill segment (encode + fsync + rename)",
)
RELOAD_SECONDS = _histogram(
    "tftpu_blockstore_reload_seconds",
    "Wall-clock to reload + CRC-check one spilled block",
)
QUARANTINES = _counter(
    "tftpu_blockstore_quarantines_total",
    "Spilled segments failing CRC/manifest checks on reload, renamed aside",
)
HOSTGATHER_BYTES = _counter(
    "tftpu_blockstore_hostgather_bytes_total",
    "Bytes of partial tables host-gathered by multi-process aggregates "
    "(the pre-shuffle path; zero when the file shuffle carries the merge)",
)

_MANIFEST = "manifest.json"
_HOST_PKL = "host.pkl"
_FORMAT_VERSION = 1


class BlockCorruptionError(RuntimeError):
    """A spilled segment failed its CRC/manifest check on reload. The
    segment has already been quarantined and counted; callers holding
    lineage should recompute (:meth:`BlockStore.get_or_recompute`)."""


@dataclass(frozen=True)
class BlockRef:
    """Handle to one block in a :class:`BlockStore` (stable across
    spill/reload; hashable so callers can keep ref → lineage maps)."""

    block_id: int
    nbytes: int
    num_rows: int


class _Entry:
    __slots__ = ("ref", "block", "spilled", "pinned", "disk_bytes")

    def __init__(self, ref: BlockRef, block: Dict[str, object]):
        self.ref = ref
        self.block = block          # None once spilled-and-dropped
        self.spilled = False        # a clean on-disk segment exists
        self.pinned = False
        self.disk_bytes = 0         # payload bytes of the live segment


def _block_nbytes(block: Dict[str, object]) -> int:
    total = 0
    for v in block.values():
        if isinstance(v, np.ndarray) and v.dtype != object:
            total += int(v.nbytes)
        else:
            # host cells (strings / ragged) — estimate via pickle on
            # spill; pre-spill use a cheap proxy so budget accounting
            # stays O(1)
            total += 64 * max(1, len(v))
    return total


def _block_rows(block: Dict[str, object]) -> int:
    for v in block.values():
        return len(v)
    return 0


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class BlockStore:
    """Spillable block container with an LRU resident-bytes budget.

    ``root`` is the spill directory (created; a private temp dir by
    default). ``budget_bytes`` bounds the bytes held in RAM across all
    resident blocks (``TFTPU_BLOCK_BUDGET_MB`` default); ``put`` spills
    the least-recently-used residents past it. Thread-safe: the
    streaming partitioner's loader thread puts while the consumer gets.
    """

    def __init__(
        self,
        root: Optional[str] = None,
        budget_bytes: Optional[int] = None,
    ):
        cfg = get_config()
        if root is None:
            # a private spill dir per store (segment ids are store-local,
            # so two stores must never share one directory); the
            # configured parent (TFTPU_BLOCKSTORE_DIR — fast local SSD
            # in production) just hosts it
            parent = cfg.blockstore_dir or None
            if parent:
                os.makedirs(parent, exist_ok=True)
            root = tempfile.mkdtemp(prefix="tftpu-blockstore-", dir=parent)
            self._owns_root = True
        else:
            os.makedirs(root, exist_ok=True)
            self._owns_root = False
        self.root = root
        self.budget_bytes = (
            cfg.block_budget_bytes if budget_bytes is None else int(budget_bytes)
        )
        self._lock = threading.RLock()
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._next_id = 0
        self._resident = 0
        self._spilled_bytes = 0

    # -- accounting ---------------------------------------------------------
    @property
    def resident_bytes(self) -> int:
        return self._resident

    @property
    def spilled_bytes(self) -> int:
        return self._spilled_bytes

    def _account(self, d_resident: int, d_spilled: int) -> None:
        self._resident += d_resident
        self._spilled_bytes += d_spilled
        # delta-tracked: the gauges aggregate over every live store in
        # the process (a set() here would clobber sibling stores);
        # close()/drop() run the same deltas in reverse, so a store's
        # contribution leaves with it
        RESIDENT_BYTES.inc(float(d_resident))
        SPILLED_BYTES.inc(float(d_spilled))

    def _seg_dir(self, block_id: int) -> str:
        return os.path.join(self.root, f"blk-{block_id:08d}")

    # -- write side ---------------------------------------------------------
    def put(self, block: Dict[str, object], pin: bool = False) -> BlockRef:
        """Register one block; spill LRU residents past the budget.
        ``pin=True`` exempts the block from LRU spilling (it can still
        be spilled explicitly via :meth:`spill`)."""
        nbytes = _block_nbytes(block)
        with self._lock:
            ref = BlockRef(self._next_id, nbytes, _block_rows(block))
            self._next_id += 1
            e = _Entry(ref, dict(block))
            e.pinned = pin
            self._entries[ref.block_id] = e
            self._account(+nbytes, 0)
            self._enforce_budget()
        return ref

    def put_spilled(self, block: Dict[str, object]) -> BlockRef:
        """Register one block and push it straight to its disk segment
        (temp → fsync → rename, CRC-stamped), dropping the in-RAM copy
        immediately. The per-sequence KV swap path (ISSUE 19): a
        swapped-out sequence's pages are cold by definition and must
        not displace the resident working set through the LRU budget —
        this never spills OTHER blocks the way :meth:`put` can."""
        nbytes = _block_nbytes(block)
        with self._lock:
            ref = BlockRef(self._next_id, nbytes, _block_rows(block))
            self._next_id += 1
            e = _Entry(ref, dict(block))
            self._entries[ref.block_id] = e
            self._account(+nbytes, 0)
            self._spill_entry(e)
        return ref

    def _enforce_budget(self) -> None:
        # called under the lock; oldest-touched first (OrderedDict
        # order). budget <= 0 is the degenerate disk-only store: every
        # unpinned block spills on arrival.
        for bid in list(self._entries):
            if self._resident <= self.budget_bytes:
                return
            e = self._entries[bid]
            if e.block is None or e.pinned:
                continue
            self._spill_entry(e)

    def spill(self, ref: BlockRef) -> None:
        """Explicitly spill one block (no-op if already on disk only)."""
        with self._lock:
            e = self._require(ref)
            if e.block is not None:
                self._spill_entry(e)

    def spill_all(self) -> None:
        with self._lock:
            for e in list(self._entries.values()):
                if e.block is not None:
                    self._spill_entry(e)

    def _spill_entry(self, e: _Entry) -> None:
        """Publish the block's segment (if not already clean on disk)
        and drop the in-RAM copy. Atomic: temp dir → fsync → rename."""
        t0 = time.perf_counter()
        if not e.spilled:
            delay_point("blockstore.spill")
            fault_point("blockstore.spill")
            seg = self._seg_dir(e.ref.block_id)
            tmp = f"{seg}.tmp.{os.getpid()}"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            cols, host = [], {}
            disk_bytes = 0
            try:
                for name, v in e.block.items():
                    if isinstance(v, np.ndarray) and v.dtype != object:
                        raw, meta = encode_array(v)
                        fn = f"c{len(cols)}.bin"
                        data = raw.tobytes()
                        with open(os.path.join(tmp, fn), "wb") as f:
                            f.write(data)
                            f.flush()
                            os.fsync(f.fileno())
                        cols.append({
                            "name": name, "kind": "dense", "file": fn,
                            "dtype": meta["dtype"], "shape": meta["shape"],
                            "crc32": zlib.crc32(data), "nbytes": len(data),
                        })
                        disk_bytes += len(data)
                    else:
                        host[name] = list(v)
                if host:
                    payload = pickle.dumps(
                        host, protocol=pickle.HIGHEST_PROTOCOL
                    )
                    with open(os.path.join(tmp, _HOST_PKL), "wb") as f:
                        f.write(payload)
                        f.flush()
                        os.fsync(f.fileno())
                    cols.append({
                        "kind": "host", "file": _HOST_PKL,
                        "names": sorted(host),
                        "crc32": zlib.crc32(payload), "nbytes": len(payload),
                    })
                    disk_bytes += len(payload)
                manifest = {
                    "format_version": _FORMAT_VERSION,
                    "block_id": e.ref.block_id,
                    "num_rows": e.ref.num_rows,
                    "columns": cols,
                }
                with open(os.path.join(tmp, _MANIFEST), "w") as f:
                    json.dump(manifest, f)
                    f.flush()
                    os.fsync(f.fileno())
                shutil.rmtree(seg, ignore_errors=True)
                os.rename(tmp, seg)
                _fsync_dir(self.root)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            e.spilled = True
            e.disk_bytes = disk_bytes
            self._account(0, +disk_bytes)
            _flight.record(
                "blockstore.spill", block_id=e.ref.block_id,
                nbytes=e.ref.nbytes, disk_bytes=disk_bytes,
            )
        e.block = None
        self._account(-e.ref.nbytes, 0)
        SPILL_SECONDS.observe(time.perf_counter() - t0)

    # -- read side ----------------------------------------------------------
    def _require(self, ref: BlockRef) -> _Entry:
        e = self._entries.get(ref.block_id)
        if e is None:
            raise KeyError(f"block {ref.block_id} is not in this store")
        self._entries.move_to_end(ref.block_id)  # LRU touch
        return e

    def get(self, ref: BlockRef, mmap: bool = False) -> Dict[str, object]:
        """Return one block. Resident blocks come back as-is; spilled
        blocks reload from their segment — **CRC-checked** by default
        (the full segment is read once), or as ``np.memmap`` views with
        ``mmap=True`` (zero read up front; the OS page cache owns
        residency — for whole-frame rebuilds where eager CRC reads
        would defeat out-of-core loading; manifest + segment sizes are
        still validated). Reloading does NOT re-admit the block into
        the resident budget: the caller owns the returned dict's
        lifetime, and dropping it frees the memory (munmap for views).
        """
        with self._lock:
            e = self._require(ref)
            if e.block is not None:
                return e.block
        t0 = time.perf_counter()
        block = self._load_segment(ref, verify=not mmap, mmap=mmap)
        RELOAD_SECONDS.observe(time.perf_counter() - t0)
        return block

    def get_or_recompute(
        self,
        ref: BlockRef,
        recompute: Callable[[], Dict[str, object]],
        mmap: bool = False,
    ) -> Dict[str, object]:
        """:meth:`get`, healing corruption from lineage: a quarantined
        reload recomputes the block, re-publishes the segment, and
        returns the fresh copy (the checkpoint/compile-store recovery
        contract applied to data blocks)."""
        try:
            return self.get(ref, mmap=mmap)
        except BlockCorruptionError:
            block = recompute()
            with self._lock:
                e = self._require(ref)
                e.block = dict(block)
                e.spilled = False
                self._account(+ref.nbytes, 0)
                self._spill_entry(e)
            return self.get(ref, mmap=mmap)

    def _load_segment(
        self, ref: BlockRef, verify: bool, mmap: bool
    ) -> Dict[str, object]:
        seg = self._seg_dir(ref.block_id)
        try:
            with open(os.path.join(seg, _MANIFEST)) as f:
                manifest = json.load(f)
            if manifest.get("format_version", 0) > _FORMAT_VERSION:
                raise ValueError(
                    f"segment format {manifest.get('format_version')} > "
                    f"{_FORMAT_VERSION}"
                )
            block: Dict[str, object] = {}
            for col in manifest["columns"]:
                path = os.path.join(seg, col["file"])
                if col["kind"] == "host":
                    with open(path, "rb") as f:
                        payload = f.read()
                    if zlib.crc32(payload) != col["crc32"]:
                        raise ValueError(f"host pickle CRC mismatch ({path})")
                    block.update(pickle.loads(payload))
                    continue
                if os.path.getsize(path) != col["nbytes"]:
                    raise ValueError(f"segment size mismatch ({path})")
                if verify:
                    with open(path, "rb") as f:
                        data = f.read()
                    if zlib.crc32(data) != col["crc32"]:
                        raise ValueError(f"column CRC mismatch ({path})")
                    raw = np.frombuffer(data, np.uint8)
                else:
                    raw = np.memmap(path, dtype=np.uint8, mode="r")
                block[col["name"]] = decode_array(
                    raw, {"dtype": col["dtype"], "shape": col["shape"]}
                )
            return block
        except (OSError, ValueError, KeyError, json.JSONDecodeError,
                pickle.UnpicklingError, EOFError) as err:
            self._quarantine(ref, seg, err)
            raise BlockCorruptionError(
                f"block {ref.block_id} segment failed verification "
                f"({type(err).__name__}: {err}); segment quarantined — "
                "recompute from lineage (get_or_recompute)"
            ) from err

    def _quarantine(self, ref: BlockRef, seg: str, err: BaseException) -> None:
        QUARANTINES.inc()
        _flight.record(
            "blockstore.quarantine", block_id=ref.block_id,
            error=type(err).__name__, message=str(err)[:200],
        )
        with self._lock:
            e = self._entries.get(ref.block_id)
            if e is not None:
                e.spilled = False
                self._account(0, -e.disk_bytes)
                e.disk_bytes = 0
        aside = f"{seg}.quarantine.{os.getpid()}"
        try:
            shutil.rmtree(aside, ignore_errors=True)
            os.rename(seg, aside)
        except OSError:  # pragma: no cover - already gone/raced
            pass
        logger.warning(
            "blockstore: quarantined segment for block %d (%s)",
            ref.block_id, err,
        )

    # -- lifecycle ----------------------------------------------------------
    def drop(self, ref: BlockRef) -> None:
        """Forget one block and delete its segment."""
        with self._lock:
            e = self._entries.pop(ref.block_id, None)
            if e is None:
                return
            if e.block is not None:
                self._account(-e.ref.nbytes, 0)
            if e.spilled:
                shutil.rmtree(self._seg_dir(ref.block_id), ignore_errors=True)
                self._account(0, -e.disk_bytes)

    def refs(self) -> List[BlockRef]:
        with self._lock:
            return [e.ref for e in self._entries.values()]

    def close(self) -> None:
        """Drop everything; delete the root if this store created it."""
        with self._lock:
            for ref in list(self.refs()):
                self.drop(ref)
            if self._owns_root:
                shutil.rmtree(self.root, ignore_errors=True)

    def __enter__(self) -> "BlockStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"BlockStore(root={self.root!r}, blocks={len(self._entries)}, "
            f"resident={self._resident}, spilled={self._spilled_bytes}, "
            f"budget={self.budget_bytes})"
        )


__all__ = ["BlockStore", "BlockRef", "BlockCorruptionError"]
