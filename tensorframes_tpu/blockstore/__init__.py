"""Out-of-core data plane (ROADMAP #3): spillable columnar block
store, streaming partitioner, and the file-based distributed shuffle.

Three layers, bottom up:

* :mod:`.store` — :class:`BlockStore`: frame blocks under a resident-
  bytes budget (``TFTPU_BLOCK_BUDGET_MB``), LRU spill to CRC-checked
  disk segments with atomic publish and quarantine-on-corruption (the
  compile-store durability contract applied to data).
* :mod:`.partitioner` — :func:`stream_chain` / :class:`SpilledFrame`:
  a lazy verb chain over a frame larger than RAM, walked block by
  block through a double-buffered pipeline, results spilling as they
  complete; peak RSS stays bounded by the budget, never the frame.
* :mod:`.shuffle` — hash-partitioned exchange of partial tables
  through per-rank spill files in the shared rendezvous dir, replacing
  the multi-process aggregate's host-gather merge; deadline-bounded
  waits name dead ranks (the PR 8 watchdog contract), CRC + retries
  ride the resilience registry.

Importing this package pre-registers every ``tftpu_blockstore_*``
metric, so expositions carry the data-plane telemetry from process
start.
"""

from .partitioner import SpilledFrame, stream_chain
from .resultstore import ResultStore
from .store import BlockCorruptionError, BlockRef, BlockStore
from . import shuffle

__all__ = [
    "BlockStore", "BlockRef", "BlockCorruptionError",
    "ResultStore", "SpilledFrame", "stream_chain", "shuffle",
]
