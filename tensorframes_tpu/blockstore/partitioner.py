"""Streaming partitioner: run a lazy verb chain over a frame larger
than host RAM, block by block, with bounded peak RSS (ROADMAP #3).

``stream_chain`` walks a **block source** (the chunked ``io.scan_csv``/
``io.scan_parquet`` generators, a :class:`SpilledFrame`, a materialized
``TensorFrame``, or any iterable of ``{column: array|list}`` blocks)
through an async double-buffered pipeline (``io.pipeline_iter`` — the
generalized ``prefetch_to_device`` machinery), applies the caller's
lazy chain to a one-block frame per chunk — the plan layer fuses each
chunk's map/filter/aggregate run into one program exactly as it does
in-memory, and the compile cache makes chunk 2..N free — and **spills
each result block to the block store as it completes**. Peak RSS is
bounded by (pipeline depth × chunk bytes + the store's resident
budget), never by the frame size.

Aggregating chains pass ``fold_fn``: each chunk's chain result is a
small partial table (spilled as it lands); after the walk the partials
union into one frame and ``fold_fn`` merges them — the UDAF
re-apply-the-combiner contract (fetches must be algebraic: sum / min /
max / count; compose mean from sum+count). With exactly-representable
values (ints, int-valued floats) the result is bit-identical to running
the same chain over the fully materialized frame — the out-of-core
bench hard-gates exactly that.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional

import numpy as np

from ..utils import get_logger
from .store import BlockRef, BlockStore, _block_rows as _rows_of

logger = get_logger(__name__)


def _host_block(block) -> dict:
    """Materialize device arrays to host numpy so the store can encode
    them (lists — host/ragged cells — pass through)."""
    return {
        k: (v if isinstance(v, (list, np.memmap)) else np.asarray(v))
        for k, v in block.items()
    }


def _empty_block(schema) -> dict:
    return {
        info.name: (
            np.empty((0,), info.dtype.np_dtype) if info.is_device else []
        )
        for info in schema
    }


@dataclass
class SpilledFrame:
    """A frame whose blocks live in a :class:`BlockStore` — the
    out-of-core result handle. ``iter_blocks`` streams blocks back
    (CRC-checked reloads); ``to_frame`` rebuilds a ``TensorFrame``
    (``mmap=True`` maps spilled segments zero-read, so rebuilding a
    larger-than-RAM frame is cheap and the OS page cache owns
    residency). ``recompute`` optionally maps refs to lineage thunks —
    a quarantined segment then heals via
    :meth:`BlockStore.get_or_recompute` instead of raising."""

    store: BlockStore
    refs: List[BlockRef]
    schema: object
    owns_store: bool = False
    recompute: dict = field(default_factory=dict)

    @property
    def num_rows(self) -> int:
        return sum(r.num_rows for r in self.refs)

    @property
    def num_blocks(self) -> int:
        return len(self.refs)

    def _load(self, ref: BlockRef, mmap: bool) -> dict:
        fn = self.recompute.get(ref.block_id)
        if fn is not None:
            return self.store.get_or_recompute(ref, fn, mmap=mmap)
        return self.store.get(ref, mmap=mmap)

    def iter_blocks(self, mmap: bool = False):
        for ref in self.refs:
            yield self._load(ref, mmap)

    def iter_frames(self, mmap: bool = False):
        """One single-block TensorFrame per stored block (the shape the
        partitioner and chunked consumers want)."""
        from ..frame import TensorFrame

        for block in self.iter_blocks(mmap=mmap):
            yield TensorFrame([block], self.schema)

    def to_frame(self, mmap: bool = True):
        """Rebuild one TensorFrame over every stored block."""
        from ..frame import TensorFrame

        blocks = [self._load(r, mmap) for r in self.refs]
        return TensorFrame(blocks or [_empty_block(self.schema)], self.schema)

    def drop(self) -> None:
        for ref in self.refs:
            self.store.drop(ref)
        self.refs = []
        if self.owns_store:
            self.store.close()


def stream_chain(
    source: Iterable,
    chain_fn: Optional[Callable] = None,
    fold_fn: Optional[Callable] = None,
    store: Optional[BlockStore] = None,
    prefetch: int = 2,
):
    """Stream ``source`` through ``chain_fn`` chunk by chunk, spilling
    results as they complete.

    ``source`` yields blocks (``{column: array|list}``), or is a
    ``TensorFrame`` / :class:`SpilledFrame`. ``chain_fn(frame) ->
    frame`` applies the lazy verb chain to each one-block chunk frame
    (None = identity). Without ``fold_fn`` returns a
    :class:`SpilledFrame` of the concatenated result blocks (row order
    = chunk order, exactly the in-memory blocking). With ``fold_fn``
    the per-chunk results are treated as partial tables and
    ``fold_fn(union_frame) -> frame`` merges them once at the end —
    returned forced, with the store's partials dropped.
    """
    from ..frame import TensorFrame, frame_from_arrays
    from ..io import pipeline_iter

    owns = store is None
    store = store or BlockStore()
    refs: List[BlockRef] = []
    schema = None
    chunks = rows_in = 0

    if isinstance(source, SpilledFrame):
        blocks_iter: Iterable = source.iter_blocks(mmap=True)
    elif hasattr(source, "blocks") and hasattr(source, "schema"):
        blocks_iter = iter(source.blocks())
    else:
        blocks_iter = iter(source)

    try:
        for chunk in pipeline_iter(blocks_iter, size=prefetch):
            f = frame_from_arrays(chunk, num_blocks=1)
            g = chain_fn(f) if chain_fn is not None else f
            out_blocks = g.blocks()
            schema = g.schema
            chunks += 1
            rows_in += _rows_of(chunk)
            for b in out_blocks:
                if _rows_of(b) == 0:
                    continue
                refs.append(store.put(_host_block(b)))
            del f, g, out_blocks, chunk  # munmap/free before next chunk
            if chain_fn is not None and chunks % 16 == 0:
                # each chunk's chain carries FRESH program identities, so
                # the fused-program cache can never hit across chunks —
                # it only fills with single-use entries (and pins their
                # executables). Clearing periodically keeps a long walk
                # O(1) in memory; evicted co-tenants merely re-lower, and
                # the persistent AOT store still serves executables.
                import gc

                from ..plan.lower import clear_fused_cache

                clear_fused_cache()
                gc.collect()
    except BaseException:
        if owns:
            store.close()
        raise

    if schema is None:
        if owns:
            store.close()
        raise ValueError("stream_chain: source yielded no chunks")
    logger.info(
        "stream_chain: %d chunk(s), %d rows in, %d result block(s), "
        "resident=%d spilled=%d",
        chunks, rows_in, len(refs), store.resident_bytes,
        store.spilled_bytes,
    )

    if fold_fn is None:
        return SpilledFrame(store, refs, schema, owns_store=owns)

    # aggregate epilogue: union the (small) partial tables and merge
    partial_blocks = [store.get(r) for r in refs]
    union = TensorFrame(
        partial_blocks or [_empty_block(schema)], schema
    )
    result = fold_fn(union)
    result.blocks()  # force before the partials are dropped
    for r in refs:
        store.drop(r)
    if owns:
        store.close()
    return result


__all__ = ["SpilledFrame", "stream_chain"]
