"""Distributed shuffle over per-rank spill files: the data-plane
exchange that replaces host-gathered partial tables (ROADMAP #3).

The PR 7/10 multi-process aggregate merged its per-rank partial tables
by **allgathering** them — every rank received every rank's partials
(O(global) per process, and impossible without working cross-process
collectives). This module is the file-transport alternative: ranks
hash-partition their rows, write one CRC-framed payload file per
destination into a shared shuffle directory (by default
``<rendezvous dir>/shuffle`` — the PR 8 fleet dir), publish a
done-marker, and read back only the payloads addressed to them. No XLA
collective is involved, so the exchange works on any backend —
including jaxlibs whose multi-process CPU collectives are missing — and
between plain OS processes enrolled via ``TFTPU_SHUFFLE_RANK`` /
``TFTPU_SHUFFLE_NPROCS`` (the test-fleet and external-launcher path).

Resilience contract:

* payload files publish atomically (write-temp → fsync → rename) and
  carry a length + CRC32 frame; torn/corrupt reads are **retried**
  (``RetryPolicy``), then **quarantined** and raised — never silently
  served;
* waiting for peers is **deadline-bounded**: a rank that dies
  mid-shuffle (kill -9) leaves its done-marker missing, and the wait
  raises :class:`~tensorframes_tpu.resilience.fleet.HungDispatchError`
  **naming the missing ranks** after a flight-recorder postmortem
  (``shuffle.hang``) — the PR 8 watchdog semantics applied to the data
  plane;
* the ``shuffle.exchange`` fault site (+ delay semantics) rides the
  resilience registry, so drills can fail or stall an exchange
  deterministically.

Transport consumers: ``ops.exchange.exchange_rows`` (joins / sorts /
repartitions pick this transport automatically when a shuffle dir is
armed), the multi-process aggregate's partial-table merge
(ops/verbs.py), and the high-level :func:`distributed_aggregate` /
:func:`distributed_join` helpers used by external process fleets.
"""

from __future__ import annotations

import os
import pickle
import shutil
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import flight as _flight
from ..observability.metrics import counter as _counter
from ..observability.metrics import histogram as _histogram
from ..resilience.faults import delay_point, fault_point
from ..resilience.retry import RetryPolicy, retry_call
from ..utils import get_logger

logger = get_logger(__name__)

SHUFFLE_BYTES = _counter(
    "tftpu_blockstore_shuffle_bytes_total",
    "Bytes published to peers through the file-based shuffle exchange",
)
EXCHANGE_SECONDS = _histogram(
    "tftpu_blockstore_shuffle_exchange_seconds",
    "Wall-clock of one full shuffle exchange (publish + barrier + read)",
)

_HDR = struct.Struct("<QI")  # payload length, crc32


class ShuffleCorruptionError(RuntimeError):
    """A peer's payload file failed its CRC frame after retries; the
    file has been quarantined."""


@dataclass
class ShuffleContext:
    """This process's identity in a file-shuffle fleet. ``root`` is the
    shared shuffle directory; ``rank``/``nprocs`` index this process.
    ``rounds`` counts completed exchanges — every rank calls every
    exchange in lockstep (the SPMD contract all verbs already assume),
    so the local counter agrees fleet-wide and names each round's
    subdirectory without any coordination."""

    root: str
    rank: int
    nprocs: int
    rounds: int = 0


_CTX: Optional[ShuffleContext] = None


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else None


def shuffle_dir() -> Optional[str]:
    """The shared shuffle directory: ``TFTPU_SHUFFLE_DIR``, else — only
    when ``TFTPU_SHUFFLE_TRANSPORT=files`` opts the fleet in — the
    ``shuffle/`` subdirectory of the rendezvous dir
    (``TFTPU_FLEET_DIR``). None = file transport disabled (supervised
    fleets keep their XLA-collective exchange unless they opt in; the
    file transport's lockstep round counter must not be imposed on
    fleets that never call it)."""
    d = os.environ.get("TFTPU_SHUFFLE_DIR")
    if d:
        return d
    if os.environ.get("TFTPU_SHUFFLE_TRANSPORT", "").lower() != "files":
        return None
    from ..resilience.fleet import rendezvous_dir

    rd = rendezvous_dir()
    return os.path.join(rd, "shuffle") if rd else None


def context() -> Optional[ShuffleContext]:
    """Resolve (and cache) this process's shuffle context, or None when
    no shuffle dir is armed. Rank/world come from
    ``TFTPU_SHUFFLE_RANK``/``TFTPU_SHUFFLE_NPROCS`` when set (external
    launchers, subprocess fleets), else from an initialized
    ``jax.distributed`` fleet, else a single-rank context."""
    global _CTX
    root = shuffle_dir()
    if root is None:
        return None
    rank, nprocs = _env_int("TFTPU_SHUFFLE_RANK"), _env_int("TFTPU_SHUFFLE_NPROCS")
    if rank is None or nprocs is None:
        import jax

        rank, nprocs = jax.process_index(), jax.process_count()
    if (
        _CTX is None
        or _CTX.root != root
        or _CTX.rank != rank
        or _CTX.nprocs != nprocs
    ):
        _CTX = ShuffleContext(root=root, rank=int(rank), nprocs=int(nprocs))
    return _CTX


def enabled() -> bool:
    """True when the file transport should carry exchanges (a shuffle
    dir is armed)."""
    return shuffle_dir() is not None


def _reset_for_tests() -> None:
    global _CTX
    _CTX = None


def _deadline_s(timeout: Optional[float]) -> float:
    if timeout is not None:
        return float(timeout)
    from ..resilience.fleet import dispatch_deadline_s

    d = dispatch_deadline_s()
    return d if d and d > 0 else 120.0


# ---------------------------------------------------------------------------
# framed payload files
# ---------------------------------------------------------------------------

def _publish(path: str, payload: bytes) -> None:
    """Atomic CRC-framed write: temp → fsync → rename (the compile
    store's publish discipline — a reader can never observe a torn
    live file)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_READ_RETRY = RetryPolicy(max_attempts=3, backoff=0.05, backoff_max=0.5)


def _read_framed(path: str, describe: str) -> bytes:
    """Read + verify one framed payload, retrying transient defects
    (the file is renamed in whole, but NFS-style caches can serve short
    reads); a persistent CRC failure quarantines the file and raises."""

    def attempt() -> bytes:
        fault_point("shuffle.exchange")
        with open(path, "rb") as f:
            hdr = f.read(_HDR.size)
            if len(hdr) != _HDR.size:
                raise OSError(f"short header in {path}")
            n, crc = _HDR.unpack(hdr)
            # validate the framed length against the file BEFORE
            # allocating: a corrupt header must raise (→ retry →
            # quarantine), not drive f.read into a petabyte MemoryError
            size = os.fstat(f.fileno()).st_size
            if n != size - _HDR.size:
                raise OSError(
                    f"framed length {n} != file payload "
                    f"{size - _HDR.size} in {path}"
                )
            payload = f.read(n)
        if len(payload) != n:
            raise OSError(f"payload length mismatch in {path}")
        if zlib.crc32(payload) != crc:
            raise OSError(f"payload CRC mismatch in {path}")
        return payload

    from ..resilience.retry import RetryError

    try:
        return retry_call(attempt, policy=_READ_RETRY, describe=describe)
    except (OSError, RetryError) as err:
        aside = f"{path}.quarantine.{os.getpid()}"
        try:
            os.replace(path, aside)
        except OSError:  # pragma: no cover - raced/remote
            pass
        _flight.record(
            "shuffle.quarantine", file=os.path.basename(path),
            error=type(err).__name__, message=str(err)[:200],
        )
        from .store import QUARANTINES

        QUARANTINES.inc()
        raise ShuffleCorruptionError(
            f"shuffle payload {path} failed verification after "
            f"{_READ_RETRY.max_attempts} attempts: {err}"
        ) from err


def _await_files(
    round_dir: str,
    want: Dict[int, str],
    deadline_s: float,
    what: str,
) -> None:
    """Block until every ``{rank: filename}`` exists, polling with a
    hard deadline. Expiry dumps a ``shuffle.hang`` postmortem and
    raises HungDispatchError NAMING the missing ranks — a SIGKILLed
    peer becomes a bounded, diagnosable abort instead of a wedged
    exchange (the PR 8 watchdog contract)."""
    t0 = time.monotonic()
    pending = dict(want)
    while pending:
        for rank, fn in list(pending.items()):
            if os.path.exists(os.path.join(round_dir, fn)):
                del pending[rank]
        if not pending:
            return
        if time.monotonic() - t0 > deadline_s:
            from ..resilience.fleet import HungDispatchError

            missing = sorted(pending)
            _flight.record(
                "shuffle.hang", what=what, missing_ranks=missing,
                waited_s=round(time.monotonic() - t0, 3),
                round_dir=round_dir,
            )
            _flight.dump(reason=f"shuffle.hang:{what}")
            raise HungDispatchError(
                f"shuffle {what}: no data from rank(s) {missing} after "
                f"{deadline_s:.1f}s (dead or wedged peer; round dir "
                f"{round_dir})"
            )
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# collective exchanges
# ---------------------------------------------------------------------------

def _round_dir(ctx: ShuffleContext, name: str) -> str:
    d = os.path.join(ctx.root, f"round-{ctx.rounds:06d}-{name}")
    os.makedirs(d, exist_ok=True)
    return d


def _finish_round(ctx: ShuffleContext, round_dir: str) -> None:
    """Mark this rank done reading and best-effort GC old rounds (only
    rounds every rank has marked fully read — a slow peer still reading
    must never lose its files)."""
    _publish(os.path.join(round_dir, f"read-{ctx.rank:05d}.done"), b"")
    ctx.rounds += 1
    if ctx.rank != 0:
        return
    try:
        for entry in os.listdir(ctx.root):
            if not entry.startswith("round-"):
                continue
            n = int(entry.split("-")[1])
            if n >= ctx.rounds - 2:
                continue
            old = os.path.join(ctx.root, entry)
            done = sum(
                os.path.exists(os.path.join(old, f"read-{r:05d}.done"))
                for r in range(ctx.nprocs)
            )
            if done == ctx.nprocs:
                shutil.rmtree(old, ignore_errors=True)
    except (OSError, ValueError):  # pragma: no cover - GC is best-effort
        pass


def exchange(
    payloads: Sequence[bytes],
    name: str = "exchange",
    timeout: Optional[float] = None,
    ctx: Optional[ShuffleContext] = None,
) -> List[bytes]:
    """All-to-all of byte payloads through per-rank spill files:
    ``payloads[dst]`` is sent from this rank to ``dst``; returns
    ``recv[src]`` — the payload each rank addressed to this one. Every
    rank must call in lockstep with the same ``name``."""
    ctx = ctx or context()
    if ctx is None:
        raise RuntimeError(
            "shuffle.exchange: no shuffle directory armed (set "
            "TFTPU_SHUFFLE_DIR, or TFTPU_FLEET_DIR for the rendezvous "
            "default)"
        )
    if len(payloads) != ctx.nprocs:
        raise ValueError(
            f"exchange needs one payload per rank ({ctx.nprocs}), "
            f"got {len(payloads)}"
        )
    t0 = time.perf_counter()
    delay_point("shuffle.exchange")
    fault_point("shuffle.exchange")
    rd = _round_dir(ctx, name)
    for dst, payload in enumerate(payloads):
        if dst == ctx.rank:
            continue  # the self-partition never touches the filesystem
        _publish(
            os.path.join(rd, f"s{ctx.rank:05d}-d{dst:05d}.part"), payload
        )
        SHUFFLE_BYTES.inc(len(payload))
    # the done marker publishes AFTER every part file: a reader that
    # sees it can trust all of this rank's parts are live
    _publish(os.path.join(rd, f"src-{ctx.rank:05d}.done"), b"")
    try:
        _await_files(
            rd,
            {r: f"src-{r:05d}.done" for r in range(ctx.nprocs)},
            _deadline_s(timeout),
            f"exchange[{name}]",
        )
        recv = [
            payloads[src]
            if src == ctx.rank
            else _read_framed(
                os.path.join(rd, f"s{src:05d}-d{ctx.rank:05d}.part"),
                describe=f"shuffle.read[{name}]",
            )
            for src in range(ctx.nprocs)
        ]
    except BaseException:
        # once OUR done marker is live, peers can complete this round —
        # a deadline expiry or failed read here must still advance the
        # local round counter, else a caller that survives the error
        # would publish into round N while peers are in N+1 and every
        # later exchange dies at the deadline blaming LIVE ranks (the
        # read-done marker stays unpublished, so the round dir is kept
        # for diagnosis)
        ctx.rounds += 1
        raise
    _finish_round(ctx, rd)
    EXCHANGE_SECONDS.observe(time.perf_counter() - t0)
    _flight.record(
        "shuffle.exchange", name=name, rank=ctx.rank, nprocs=ctx.nprocs,
        sent_bytes=[len(p) for p in payloads],
        recv_bytes=[len(b) for b in recv],
    )
    return recv


def allshare(
    payload: bytes,
    name: str = "allshare",
    timeout: Optional[float] = None,
    ctx: Optional[ShuffleContext] = None,
) -> List[bytes]:
    """Allgather of one payload per rank (each rank publishes once,
    reads all) — the final-result share that replaces
    ``process_allgather`` for small replicated tables."""
    ctx = ctx or context()
    if ctx is None:
        raise RuntimeError("shuffle.allshare: no shuffle directory armed")
    t0 = time.perf_counter()
    delay_point("shuffle.exchange")
    fault_point("shuffle.exchange")
    rd = _round_dir(ctx, name)
    _publish(os.path.join(rd, f"all-{ctx.rank:05d}.part"), payload)
    SHUFFLE_BYTES.inc(len(payload))
    try:
        _await_files(
            rd,
            {r: f"all-{r:05d}.part" for r in range(ctx.nprocs)},
            _deadline_s(timeout),
            f"allshare[{name}]",
        )
        out = [
            payload
            if r == ctx.rank
            else _read_framed(
                os.path.join(rd, f"all-{r:05d}.part"),
                describe=f"shuffle.allshare[{name}]",
            )
            for r in range(ctx.nprocs)
        ]
    except BaseException:
        ctx.rounds += 1  # stay in lockstep with peers (see exchange)
        raise
    _finish_round(ctx, rd)
    EXCHANGE_SECONDS.observe(time.perf_counter() - t0)
    return out


def vote_all(ok: bool, name: str = "vote", timeout: Optional[float] = None) -> bool:
    """File-based uniform eligibility vote (the collective-free
    ``uniform_ok``): True only when EVERY rank voted True — all ranks
    take the same branch before any further exchange."""
    flags = allshare(b"\x01" if ok else b"\x00", name=name, timeout=timeout)
    return all(b == b"\x01" for b in flags)


def barrier(name: str = "barrier", timeout: Optional[float] = None) -> None:
    """File-based fleet barrier with the shuffle deadline semantics."""
    allshare(b"", name=name, timeout=timeout)


# ---------------------------------------------------------------------------
# table-level helpers
# ---------------------------------------------------------------------------

def _pack_table(cols: Dict[str, object], sel: Optional[np.ndarray]) -> bytes:
    sub = {}
    for n, v in cols.items():
        if isinstance(v, list):
            a = np.asarray(v, dtype=object)
            sub[n] = list(a[sel]) if sel is not None else list(a)
        else:
            a = np.asarray(v)
            sub[n] = a[sel] if sel is not None else a
    return pickle.dumps(sub, protocol=pickle.HIGHEST_PROTOCOL)


def _concat_tables(tables: List[Dict[str, object]]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    if not tables:
        return out
    for n in tables[0]:
        pieces = [t[n] for t in tables]
        if isinstance(pieces[0], list):
            merged: List[object] = []
            for p in pieces:
                merged.extend(p)
            out[n] = merged
        else:
            out[n] = np.concatenate([np.asarray(p) for p in pieces]) \
                if pieces else pieces
    return out


def shuffle_rows(
    cols: Dict[str, object],
    part: np.ndarray,
    name: str = "rows",
    timeout: Optional[float] = None,
) -> Dict[str, object]:
    """Hash/range-partitioned row exchange through spill files: row i
    of ``cols`` travels to rank ``part[i]``; returns the rows every
    rank sent HERE, in (source rank, local row order) — the same
    deterministic contract as ``ops.exchange.exchange_rows``."""
    ctx = context()
    if ctx is None:
        raise RuntimeError("shuffle.shuffle_rows: no shuffle directory armed")
    part = np.asarray(part)
    payloads = [
        _pack_table(cols, np.flatnonzero(part == dst))
        for dst in range(ctx.nprocs)
    ]
    received = exchange(payloads, name=name, timeout=timeout)
    return _concat_tables([pickle.loads(b) for b in received])


def allshare_table(
    cols: Dict[str, object],
    name: str = "table",
    timeout: Optional[float] = None,
) -> Dict[str, object]:
    """Share one small table per rank with every rank; returns the
    concatenation in rank order (replicated everywhere)."""
    shares = allshare(_pack_table(cols, None), name=name, timeout=timeout)
    return _concat_tables([pickle.loads(b) for b in shares])


# ---------------------------------------------------------------------------
# distributed relational verbs over process-local frames
# ---------------------------------------------------------------------------

def _frame_cols(frame) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for info in frame.schema:
        v = frame.column_values(info.name)
        out[info.name] = list(v) if v.dtype == object else v
    return out


def distributed_aggregate(
    local_frame,
    keys: Sequence[str],
    agg_fn,
    name: str = "agg",
    timeout: Optional[float] = None,
):
    """Shuffled keyed aggregation across a file-shuffle fleet — zero
    host-gathered partial tables.

    Each rank holds ``local_frame`` (its rows of the global frame) and
    an ``agg_fn(frame) -> frame`` building the aggregate through the
    normal verb engine (any fused map/filter chain upstream included).
    Per rank: local partials → hash-partition by group key → spill-file
    exchange → re-apply ``agg_fn`` to the received partials (the UDAF
    merge contract: fetches must be algebraic — sum/min/max/count; a
    mean must be composed from sum+count) → allshare the small finals →
    groups ordered lexicographically (the single-process host path's
    ordering). Every rank returns the identical replicated result."""
    from ..frame import frame_from_arrays
    from ..ops.exchange import partition_by_hash
    from ..ops.keys import group_ids

    ctx = context()
    if ctx is None:
        raise RuntimeError(
            "distributed_aggregate: no shuffle directory armed"
        )
    partial = agg_fn(local_frame)
    pcols = _frame_cols(partial)
    key_arrays = [
        np.asarray(pcols[k], dtype=object)
        if isinstance(pcols[k], list) else np.asarray(pcols[k])
        for k in keys
    ]
    part = partition_by_hash(key_arrays, ctx.nprocs)
    mine = shuffle_rows(pcols, part, name=f"{name}.partials", timeout=timeout)
    n_mine = len(next(iter(mine.values()))) if mine else 0
    if n_mine:
        merged_frame = agg_fn(frame_from_arrays(mine, num_blocks=1))
        merged = _frame_cols(merged_frame)
    else:
        merged = {n: (v[:0] if not isinstance(v, list) else [])
                  for n, v in pcols.items()}
    union = allshare_table(merged, name=f"{name}.finals", timeout=timeout)
    ukeys = [
        np.asarray(union[k], dtype=object)
        if isinstance(union[k], list) else np.asarray(union[k])
        for k in keys
    ]
    if not len(ukeys[0]):
        return frame_from_arrays(union, num_blocks=1)
    # partitions are key-disjoint: exactly one row per group survives;
    # group_ids orders groups lexicographically — the oracle's layout
    ids, _, num_groups = group_ids(ukeys)
    perm = np.empty(num_groups, np.int64)
    perm[ids] = np.arange(len(ids))
    ordered = {
        n: ([v[i] for i in perm] if isinstance(v, list)
            else np.asarray(v)[perm])
        for n, v in union.items()
    }
    return frame_from_arrays(ordered, num_blocks=1)


def distributed_join(
    left_frame,
    right_frame,
    on,
    name: str = "join",
    how: str = "inner",
    timeout: Optional[float] = None,
):
    """Shuffled hash join across a file-shuffle fleet: both sides'
    process-local rows hash-partition on the join key, exchange through
    spill files, and each rank joins only its key partition through the
    normal ``TensorFrame.join``. Returns the replicated union of every
    rank's partition as a ``{column: array|list}`` table in (rank,
    local join order) — canonicalize by sorting when comparing against
    a single-process oracle, whose row order differs."""
    from ..frame import frame_from_arrays

    on = [on] if isinstance(on, str) else list(on)
    if how != "inner":
        # a rank whose opposite-side partition is empty would have to
        # emit fill-extended rows to honor left/right/outer — that
        # needs the fill_value plumbing TensorFrame.join requires for
        # those hows; refusing beats silently dropping the unmatched
        # rows of empty-partition ranks
        raise ValueError(
            f"distributed_join supports how='inner' only (got {how!r}); "
            "outer joins across the shuffle need fill-value plumbing — "
            "run the replicated TensorFrame.join for those"
        )
    ctx = context()
    if ctx is None:
        raise RuntimeError("distributed_join: no shuffle directory armed")
    from ..ops.exchange import partition_by_hash

    sides = {}
    for tag, f in (("L", left_frame), ("R", right_frame)):
        cols = _frame_cols(f)
        key_arrays = [
            np.asarray(cols[k], dtype=object)
            if isinstance(cols[k], list) else np.asarray(cols[k])
            for k in on
        ]
        part = partition_by_hash(key_arrays, ctx.nprocs)
        sides[tag] = shuffle_rows(
            cols, part, name=f"{name}.{tag}", timeout=timeout
        )

    def rows_of(t):
        return len(next(iter(t.values()))) if t else 0

    if rows_of(sides["L"]) and rows_of(sides["R"]):
        local = frame_from_arrays(sides["L"], num_blocks=1).join(
            frame_from_arrays(sides["R"], num_blocks=1), on=on, how=how
        )
        lcols = _frame_cols(local)
    else:
        # a rank can hold keys on only one side — its inner partition
        # is empty; share zero rows under the joined schema (left
        # columns then right non-key columns, dtypes preserved by the
        # shuffled empties so peers' concat stays typed)
        lcols = {}
        for src in (sides["L"], sides["R"]):
            for n, v in src.items():
                if n not in lcols:
                    lcols[n] = (
                        [] if isinstance(v, list) else np.asarray(v)[:0]
                    )
    return allshare_table(lcols, name=f"{name}.union", timeout=timeout)


__all__ = [
    "ShuffleContext", "ShuffleCorruptionError", "context", "enabled",
    "shuffle_dir", "exchange", "allshare", "vote_all", "barrier",
    "shuffle_rows", "allshare_table", "distributed_aggregate",
    "distributed_join",
]
