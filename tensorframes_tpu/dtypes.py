"""Scalar dtype registry: the one-to-one frame ⇄ numpy ⇄ XLA type mapping.

Capability parity with the reference's dtype registry
(reference: src/main/scala/org/tensorframes/impl/datatypes.scala):

* a closed set of supported scalar types (datatypes.scala:265-267):
  float64, float32, int32, int64, plus *host-only* binary/string columns
  (datatypes.scala:571-622 — strings are single-scalar, never shipped to the
  accelerator; TPUs do not execute string ops, so string/binary columns stay
  resident on the host and are passed through verbs untouched).
* strictly one-to-one mapping with **no implicit casting** anywhere
  (datatypes.scala:155-161). A float64 column feeds only a float64
  placeholder; mismatches are errors raised by the validation layer.

TPU-native extensions beyond the reference set: bfloat16 / float16 (MXU
native), int8/uint8, and bool — all first-class on XLA:TPU. float64/int64
require ``jax_enable_x64`` which :mod:`tensorframes_tpu` enables at import
so the reference's Double/Long-typed examples run unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BFLOAT16 = None


@dataclasses.dataclass(frozen=True)
class ScalarType:
    """One supported scalar type.

    ``device`` — whether columns of this type may be placed in HBM and fed
    to compiled programs. Host-only types (string / binary / object) ride
    along in verbs as pass-through columns.
    """

    name: str
    np_dtype: Optional[np.dtype]  # None for host object columns
    device: bool
    # Zero element used for padding blocks up to bucket sizes.
    zero: object = 0

    def __repr__(self) -> str:
        return f"ScalarType({self.name})"

    @property
    def jax_dtype(self):
        if not self.device:
            raise TypeError(f"{self.name} columns are host-only; no device dtype")
        return self.np_dtype


float64 = ScalarType("float64", np.dtype(np.float64), True, 0.0)
float32 = ScalarType("float32", np.dtype(np.float32), True, 0.0)
int32 = ScalarType("int32", np.dtype(np.int32), True, 0)
int64 = ScalarType("int64", np.dtype(np.int64), True, 0)
# TPU-native extras
bfloat16 = (
    ScalarType("bfloat16", _BFLOAT16, True, 0.0) if _BFLOAT16 is not None else None
)
float16 = ScalarType("float16", np.dtype(np.float16), True, 0.0)
int8 = ScalarType("int8", np.dtype(np.int8), True, 0)
uint8 = ScalarType("uint8", np.dtype(np.uint8), True, 0)
bool_ = ScalarType("bool", np.dtype(np.bool_), True, False)
# Host-only (≙ reference's String/Binary single-scalar columns,
# datatypes.scala:577-581)
string = ScalarType("string", None, False, "")
binary = ScalarType("binary", None, False, b"")

_DEVICE_TYPES = [t for t in (float64, float32, bfloat16, float16, int64, int32, int8, uint8, bool_) if t is not None]
_ALL_TYPES = _DEVICE_TYPES + [string, binary]

_BY_NAME: Dict[str, ScalarType] = {t.name: t for t in _ALL_TYPES}
_BY_NP: Dict[np.dtype, ScalarType] = {t.np_dtype: t for t in _DEVICE_TYPES}


class UnsupportedTypeError(TypeError):
    """A dtype outside the registry. ≙ the reference's failures in
    ``SupportedOperations.opsFor`` (datatypes.scala:265-324)."""


# 64-bit → 32-bit demotion table for the TPU x64 story (VERDICT r1
# next-step 2): f64 matmuls/reductions on TPU are software-emulated, so
# reference-parity Double/Long columns can optionally demote at the
# device boundary.
_DEMOTIONS = {float64: float32, int64: int32}


def demote(t: ScalarType) -> ScalarType:
    """The 32-bit device type a 64-bit column demotes to (identity for
    everything else)."""
    return _DEMOTIONS.get(t, t)


def demotion_active() -> bool:
    """True when ``configure(demote_x64_on_tpu=...)`` applies to the
    current backend: ``"always"`` forces it (tests/CPU measurement);
    ``True`` restricts it to real TPU backends."""
    from .config import get_config

    cfg = getattr(get_config(), "demote_x64_on_tpu", False)
    if cfg == "always":
        return True
    if cfg:
        import jax

        return jax.default_backend() == "tpu"
    return False


def default_float() -> ScalarType:
    """The framework's float *policy* dtype for constructed constants
    (DSL ``zeros``/``ones``/``fill``): float32 whenever the x64 demotion
    pass is active or x64 is disabled — otherwise float64 (reference
    parity: Double columns, datatypes.scala:265-267).

    Before this policy existed the DSL constructors hard-coded
    ``np.float64`` and silently relied on the later demotion pass to
    cast it back down; the static analyzer's TFG102 rule now flags that
    pattern (see docs/analysis.md#tfg102)."""
    from .config import get_config

    if demotion_active() or not get_config().enable_x64:
        return float32
    return float64


def all_types():
    return list(_ALL_TYPES)


def device_types():
    return list(_DEVICE_TYPES)


def by_name(name: str) -> ScalarType:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise UnsupportedTypeError(
            f"Unsupported scalar type {name!r}. Supported: {sorted(_BY_NAME)}"
        ) from None


def from_numpy(dtype) -> ScalarType:
    """Resolve a numpy dtype (or anything np.dtype accepts) to a ScalarType.

    Object / str / bytes dtypes map to the host-only types. No widening, no
    narrowing — an unregistered dtype is an error (datatypes.scala:155-161).
    """
    try:
        dt = np.dtype(dtype)
    except TypeError:
        raise UnsupportedTypeError(f"Not a dtype: {dtype!r}") from None
    if dt in _BY_NP:
        return _BY_NP[dt]
    if dt.kind in ("U", "S"):
        return string if dt.kind == "U" else binary
    if dt.kind == "O":
        return string
    raise UnsupportedTypeError(
        f"Unsupported dtype {dt}. Supported device types: "
        f"{[t.name for t in _DEVICE_TYPES]}; host types: ['string', 'binary']"
    )


def from_python_value(v) -> ScalarType:
    """Infer the ScalarType of one Python scalar cell (analyze path).

    Python ``float`` → float64 and ``int`` → int64, matching the reference's
    inference from Spark SQL DoubleType/LongType rows; numpy scalars map
    through their dtype exactly.
    """
    if isinstance(v, bool):  # before int — bool is an int subclass
        return bool_
    if isinstance(v, (bytes, bytearray)):
        return binary
    if isinstance(v, str):
        return string
    if isinstance(v, int):
        return int64
    if isinstance(v, float):
        return float64
    if isinstance(v, np.generic):
        return from_numpy(v.dtype)
    if isinstance(v, np.ndarray):
        return from_numpy(v.dtype)
    raise UnsupportedTypeError(f"Unsupported cell value of type {type(v).__name__}")
