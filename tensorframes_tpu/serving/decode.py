"""Iterative decode engine: token-level continuous batching (ISSUE 11).

The flush batcher (batcher.py) coalesces ROW-independent requests into
one dispatch each — right for stateless scoring, wrong for
autoregressive decode, where a request is a *sequence* of dependent
steps against growing KV state. This module is the vLLM-style engine
the ROADMAP's "heavy traffic" target needs: a persistent decode loop
where per-request sequence slots **join and leave the running batch
every step**, over a block-paged KV pool
(:class:`~tensorframes_tpu.serving.kvpool.PagedKVPool`) shared by all
sequences.

Scheduling shape, per loop iteration:

1. **join** — poll the admission queue (a pull-mode
   :class:`~tensorframes_tpu.serving.batcher.ContinuousBatcher`: its
   expirer thread covers requests waiting for a free slot, so a full
   pool can never hold one past its deadline) while slots and prompt
   pages are free; each join runs one **prefill** step (the prompt
   chunk, padded to a ladder bucket) producing the first token.
2. **decode** — one batched single-token step over every running slot,
   padded to the slot-count bucket ladder. A slot that needs a new KV
   page and finds the pool empty triggers **preemption**: the
   youngest running sequence is evicted (pages freed, counted) and
   requeued at the queue head with its generated tokens intact; on
   rejoin it replays prefill + teacher-forced decode through the SAME
   executables, so its continuation is bit-identical to never having
   been preempted (asserted, not assumed). The oldest sequence is
   never preempted and the pool floor guarantees its horizon fits —
   forward progress is structural, not probabilistic.
3. **leave** — finished sequences resolve their futures, free their
   pages, and their slots are immediately joinable.

Zero-steady-state-compile contract: both phases dispatch through
``aot_jit`` at shapes drawn from ONE ladder —
``compilecache.decode_warmup_grid`` (slot-count buckets for decode,
prompt-length buckets for prefill, both delegating to
``serving_row_buckets``) — and ``start()`` warms every point of that
grid, so a warmed engine sustains any join/leave mix without touching
XLA. Decode is greedy (argmax inside the step executable): determinism
is what makes preemption-replay and the batched-vs-solo bit-identity
gate (bench.py hard-gates it) meaningful.

KV memory hierarchy (ISSUE 19), both tiers off by default:

* ``prefix_cache=True`` arms the content-addressed prefix cache: a
  fresh prompt's page-granular prefix is hash-matched against pages
  other sequences already prefilled and published read-only
  (refcounted; ``PagedKVPool.check()``'s partition extends to them); a
  hit skips those prefill chunks — the suffix runs through a dedicated
  gather-attending prefill executable, or copy-on-extend duplicates a
  shared ragged-tail page and teacher-forces only the final prompt
  token — so TTFT drops to the unshared remainder while outputs stay
  bit-identical to the cold path (same quantized-KV math end to end).
* ``kv_swap=True`` arms per-sequence host-swap: a preempted sequence's
  own pages (slot scales and generated prefix included) travel to a
  CRC-stamped BlockStore segment, and rejoin RESTORES them instead of
  recompute-replaying. Replay data is kept alongside every swap
  snapshot: segment corruption falls back to the replay path, counted
  (``tftpu_kvswap_fallback_total``), so no store problem can lose a
  request.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..observability import events as _events
from ..observability import flight as _flight
from ..utils import get_logger
from ..validation import ValidationError
from . import metrics as m
from .batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    RejectedError,
    ResultFuture,
    ServingError,
    _Request,
)
from .kvpool import PagedKVPool, PoolExhaustedError

logger = get_logger(__name__)

__all__ = ["DecodeConfig", "DecodeEngine", "prefix_cache_events"]

# Prefix-cache ineligibility evidence for lint_plan's TFG113 rule: one
# entry per (endpoint, reason) the first time it arises, bounded. The
# analyzer reads this through prefix_cache_events() — serving state
# never imports analysis, only the other way around.
_PREFIX_INELIGIBLE: Deque[Dict[str, object]] = collections.deque(
    maxlen=128
)
_PREFIX_INELIGIBLE_SEEN: set = set()


def prefix_cache_events() -> List[Dict[str, object]]:
    """Recent prefix-cache ineligibility evidence (deduplicated per
    endpoint and reason) — the TFG113 rule's input."""
    return list(_PREFIX_INELIGIBLE)


@dataclasses.dataclass
class DecodeConfig:
    """Sizing knobs for one decode endpoint.

    ``max_slots`` — running-batch width (slot counts pad through the
    bucket ladder, so the top bucket is what compiles).
    ``page_size`` — KV positions per pool page.
    ``num_pages`` — total pool pages incl. the reserved null page;
    ``None`` auto-sizes to hold every slot's full horizon (no
    preemption under any admissible load). Size it smaller to trade
    preemptions for HBM.
    ``max_prompt_len`` / ``max_new_tokens`` — per-request bounds; their
    sum is the decode horizon (must fit the model's ``max_seq_len``).
    ``max_queue_requests`` — admission bound; past it submits shed with
    ``RejectedError(reason="queue_full")``.
    ``default_deadline_s`` — total-elapsed deadline applied when a
    request carries none (``RetryPolicy.deadline_s`` semantics; expiry
    covers queue AND slot wait — once running, a sequence completes).
    ``warmup`` — precompile the slot × phase bucket grid at start.
    ``kv_swap`` — host-swap a preempted sequence's pages to a
    BlockStore segment and restore them on rejoin (counted fallback to
    recompute-replay on corruption). ``swap_dir`` roots the swap store
    (default: a private temp dir, deleted at stop).
    ``prefix_cache`` — share read-only prompt-prefix pages across
    requests by content hash (refcounted, copy-on-extend at the ragged
    tail, evicted only at refcount 0).
    """

    max_slots: int = 8
    page_size: int = 16
    num_pages: Optional[int] = None
    max_prompt_len: int = 32
    max_new_tokens: int = 16
    max_queue_requests: int = 1024
    default_deadline_s: Optional[float] = None
    warmup: bool = True
    kv_swap: bool = False
    prefix_cache: bool = False
    swap_dir: Optional[str] = None


class _Seq:
    """One running sequence slot (engine-thread private)."""

    __slots__ = ("req", "seq", "prompt", "want", "pos", "joined",
                 "generated", "replay")

    def __init__(self, req: _Request, seq: int, prompt: np.ndarray,
                 want: int, joined: int):
        self.req = req
        self.seq = seq
        self.prompt = prompt
        self.want = want
        self.pos = int(prompt.shape[0])  # next KV position to write
        self.joined = joined             # monotonic join counter
        self.generated: List[int] = []
        self.replay: Optional[Deque[int]] = None


class DecodeEngine:
    """The persistent decode loop over one model + one paged KV pool.

    Usually constructed through
    :meth:`~tensorframes_tpu.serving.Server.register_decode`, which
    routes ``Server.submit(name, {"prompt": ...})`` here and exposes it
    over the HTTP sidecar. Standalone use::

        eng = DecodeEngine("gen", gpt_tiny_cfg, params, DecodeConfig())
        eng.start()
        fut = eng.submit({"prompt": np.arange(7, dtype=np.int32)})
        fut.result(60.0)["tokens"]     # [1, max_new_tokens] int32
        eng.stop(drain=True)
    """

    def __init__(self, name: str, model_cfg, params,
                 config: Optional[DecodeConfig] = None):
        from ..compilecache import decode_warmup_grid
        from ..models import generation as gen
        from ..ops.executor import aot_jit

        self.name = name
        self.cfg = model_cfg
        self.params = params
        self.config = cfg = config or DecodeConfig()
        if cfg.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if cfg.max_prompt_len < 1 or cfg.max_new_tokens < 1:
            raise ValueError(
                "max_prompt_len and max_new_tokens must be >= 1"
            )
        horizon = cfg.max_prompt_len + cfg.max_new_tokens
        if horizon > model_cfg.max_seq_len:
            raise ValueError(
                f"decode horizon {horizon} (max_prompt_len + "
                f"max_new_tokens) exceeds the model's max_seq_len="
                f"{model_cfg.max_seq_len}"
            )
        max_pages = -(-horizon // cfg.page_size)
        num_pages = cfg.num_pages
        if num_pages is None:
            # auto-size: every slot can hold a full horizon — the
            # no-preemption configuration
            num_pages = 1 + cfg.max_slots * max_pages
        self._pool = PagedKVPool(
            model_cfg, num_pages, cfg.page_size, max_pages
        )
        grid = decode_warmup_grid(cfg.max_slots, cfg.max_prompt_len)
        self._slot_buckets = grid["decode"]
        self._prefill_buckets = grid["prefill"]
        self._prefill = aot_jit(
            gen.paged_prefill_fn(model_cfg, cfg.page_size, max_pages),
            label=f"decode.prefill[{name}]",
        )
        # decode-attention lowering: a counted cost-model decision made
        # ONCE per engine (ISSUE 12) — batched and solo steps trace the
        # same choice, so the batched==solo / preemption-replay
        # bit-identity gates hold whichever lowering wins. The choice
        # also reaches the compile-cache fingerprint (kernels token),
        # so a disable_pallas() flip can never serve a stale executable.
        from ..plan import stats as _pstats
        from ..plan.lower import _note_decision, _note_flip
        from ..plan.rules import decide_decode_attention

        decision = decide_decode_attention(
            model_cfg.num_heads, model_cfg.head_dim, cfg.page_size,
            max_pages,
            observed_walls=_pstats.strategy_walls("decode_attention"),
        )
        _note_decision(decision)
        _note_flip(decision)
        self._attn_kernel: Optional[str] = (
            "pallas" if decision.kind == "pallas_decode_attn" else None
        )
        self._step = aot_jit(
            gen.paged_decode_step_fn(
                model_cfg, cfg.page_size, max_pages,
                attn_kernel=self._attn_kernel,
            ),
            label=f"decode.step[{name}]",
        )
        # KV memory hierarchy executables (ISSUE 19) — all fixed-shape,
        # warmed alongside the grid, so neither tier costs a
        # steady-state compile
        self._prefix_cache = bool(cfg.prefix_cache)
        self._kv_swap = bool(cfg.kv_swap)
        self._suffix_prefill = None
        self._extract = self._restore = self._copy_page = None
        if self._prefix_cache:
            self._suffix_prefill = aot_jit(
                gen.paged_suffix_prefill_fn(
                    model_cfg, cfg.page_size, max_pages
                ),
                label=f"decode.suffix_prefill[{name}]",
            )
        if self._prefix_cache or self._kv_swap:
            ex_fn, rs_fn, cp_fn = gen.paged_page_ops_fns(max_pages)
            if self._kv_swap:
                self._extract = aot_jit(
                    ex_fn, label=f"decode.kvswap.extract[{name}]"
                )
                self._restore = aot_jit(
                    rs_fn, label=f"decode.kvswap.restore[{name}]"
                )
            if self._prefix_cache:
                self._copy_page = aot_jit(
                    cp_fn, label=f"decode.prefix.copy[{name}]"
                )
        self._swap_store = None
        self._swap: Dict[_Request, Dict[str, object]] = {}
        # PR 18 follow-up: swap segments survive the engine. stop()
        # PARKS pending keyed sequences' segments (trace_id → swap
        # snapshot) instead of dropping them; spill() folds them into
        # the whole-pool snapshot; restore() on a fresh engine re-homes
        # them here, and a redriven request with the same trace id
        # resumes from its pages (no prefill recompute).
        self._swap_parked: Dict[str, Dict[str, object]] = {}
        self._swap_restored: Dict[str, Dict[str, object]] = {}
        # first-page fingerprints of fresh prompts on an UNARMED
        # engine: a repeat is hard evidence prefill work was shareable
        # (the TFG113 store_unarmed signal) — bounded, never grows past
        # the cap
        self._seen_first_pages: set = set()
        self._swap_outs = 0
        self._swap_resumes = 0
        self._swap_fallbacks = 0
        self._prefix_hits = 0
        self._prefix_misses = 0
        # admission: pull mode — no worker thread; the engine loop
        # drains it, its expirer covers the slot-wait queue
        self._admission = ContinuousBatcher(
            name, None,
            max_batch_rows=1,
            max_latency_s=0.0,
            max_queue_rows=cfg.max_queue_requests,
        )
        self._slots: List[Optional[_Seq]] = [None] * cfg.max_slots
        self._resume: Dict[_Request, List[int]] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._starting = False
        self._stopping = False
        self._drain = True
        self._next_seq = 0
        self._join_counter = 0

    def _run_step(self, *args):
        """Dispatch one batched decode step, honoring the pallas
        recovery contract: a Mosaic kernel-compile failure trips the
        process-wide kill-switch (fused-cache invalidation included),
        rebuilds the step on the XLA gather chain, and retries — a
        custom kernel must never take down the engine."""
        from .. import kernels as _kernels
        from ..plan.lower import observe_strategy_wall

        t_step = time.perf_counter()
        try:
            out = self._step(*args)
        except Exception as e:
            from ..models import generation as gen
            from ..ops import segment as _segment
            from ..ops.executor import aot_jit

            if (
                self._attn_kernel is None
                or not _segment.pallas_enabled()
                or "Mosaic" not in str(e)
            ):
                raise
            _segment.disable_pallas(
                f"{type(e).__name__} in decode-attention kernel"
            )
            self._attn_kernel = None
            self._step = aot_jit(
                gen.paged_decode_step_fn(
                    self.cfg, self.config.page_size,
                    self._pool.max_pages_per_seq, attn_kernel=None,
                ),
                label=f"decode.step[{self.name}]",
            )
            t_step = time.perf_counter()  # rebuilt step: time XLA only
            out = self._step(*args)
        observe_strategy_wall(
            "decode_attention",
            "pallas_decode_attn" if self._attn_kernel is not None
            else "xla_decode_attn",
            time.perf_counter() - t_step,
        )
        if self._attn_kernel is not None:
            _kernels.note_dispatch(
                "decode_attn", _kernels.interpret_mode()
            )
        return out

    # -- introspection ------------------------------------------------------

    @property
    def pool(self) -> PagedKVPool:
        return self._pool

    @property
    def running(self) -> bool:
        return self._running

    def counters(self) -> Dict[str, object]:
        """Admission counters (shared batcher snapshot) + engine state."""
        snap = self._admission.counters()
        with self._lock:
            snap["running_slots"] = sum(
                1 for s in self._slots if s is not None
            )
        snap["free_pages"] = self._pool.num_free
        snap["allocatable_pages"] = self._pool.num_allocatable
        snap["shared_pages"] = self._pool.num_shared
        snap["swap_outs"] = self._swap_outs
        snap["swap_resumes"] = self._swap_resumes
        snap["swap_fallbacks"] = self._swap_fallbacks
        snap["prefix_hits"] = self._prefix_hits
        snap["prefix_misses"] = self._prefix_misses
        return snap

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DecodeEngine":
        with self._lock:
            if self._running or self._starting:
                return self
            if self._thread is not None and self._thread.is_alive():
                # a previous stop(timeout=...) expired with the loop
                # still draining: starting a SECOND loop over the same
                # slots/pool would corrupt both — refuse until it exits
                raise ServingError(
                    f"decode engine {self.name!r} is still draining "
                    "from a timed-out stop(); retry once it finishes"
                )
            self._starting = True
        t0 = time.perf_counter()
        try:
            # _running commits only AFTER warmup + admission + the loop
            # thread all succeed: a failed warm must leave the engine
            # cleanly restartable, not a zombie that reports running
            # while every submit sheds as 'closed'
            self._pool.reopen()  # no-op unless restarting after stop()
            if self._kv_swap and self._swap_store is None:
                from ..blockstore import BlockStore

                # budget 0: swap segments go straight to disk anyway
                # (put_spilled), and the swap store must never hold
                # pages resident on behalf of the pool it is relieving
                self._swap_store = BlockStore(
                    root=self.config.swap_dir, budget_bytes=0,
                )
            if self.config.warmup:
                self._warm()
            self._admission.start()
            thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"tfs-decode-{self.name}",
            )
            with self._lock:
                self._thread = thread
                self._stopping = False
                self._running = True
            thread.start()
        finally:
            with self._lock:
                self._starting = False
        _flight.record(
            "serving.decode.start", endpoint=self.name,
            slots=self.config.max_slots,
            pages=self._pool.num_pages,
            page_size=self.config.page_size,
            warmup_s=round(time.perf_counter() - t0, 6),
        )
        return self

    def _warm(self) -> None:
        """Execute every point of the slot × phase bucket grid once
        against null tables (writes land in the null page, results are
        discarded — the pool state object is never reassigned). Unlike
        ``warm_program`` this executes, not just compiles: the grid is
        tiny, and the run also faults in the gather/scatter kernels."""
        t0 = time.perf_counter()
        cols = self._pool.columns
        null = self._pool.null_table()
        for tb in self._prefill_buckets:
            self._prefill(
                self.params, cols, np.zeros(tb, np.int32),
                np.int32(1), null,
            )
        for sb in self._slot_buckets:
            self._run_step(
                self.params, cols, np.zeros(sb, np.int32),
                np.zeros(sb, np.int32),
                np.zeros((sb, self._pool.max_pages_per_seq), np.int32),
            )
        maxp = self._pool.max_pages_per_seq
        if self._suffix_prefill is not None:
            for tb in self._prefill_buckets:
                self._suffix_prefill(
                    self.params, cols, np.zeros(tb, np.int32),
                    np.int32(0), np.int32(1), null,
                )
        if self._copy_page is not None:
            # null page onto itself — garbage by contract either way
            self._copy_page(cols, np.int32(0), np.int32(0))
        if self._extract is not None:
            idx = np.zeros(maxp, np.int32)
            ex = self._extract(cols, idx)
            self._restore(
                cols, idx,
                np.asarray(ex["k"]), np.asarray(ex["v"]),
                np.asarray(ex["k_scale"]), np.asarray(ex["v_scale"]),
            )
        logger.info(
            "decode warmup[%s]: prefill buckets %s + decode buckets %s "
            "in %.2fs", self.name, self._prefill_buckets,
            self._slot_buckets, time.perf_counter() - t0,
        )

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Close admission; ``drain=True`` completes every admitted AND
        queued sequence first, ``drain=False`` fails queued and running
        requests with :class:`ServingError`. Bounded by ``timeout``."""
        with self._lock:
            if not self._running and self._thread is None:
                # never started (or already stopped): still withdraw
                # the pool from the process-wide free-pages gauge — a
                # registered-but-never-started engine's pages must not
                # inflate other engines' headroom signal forever
                self._pool.close()
                return
            self._stopping = True
            self._drain = drain
            thread = self._thread
        self._admission.close(drain=drain)
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                logger.warning(
                    "decode engine %r still draining after stop "
                    "timeout", self.name,
                )
        self._admission.stop(drain=drain, timeout=timeout)
        with self._lock:
            self._running = False
            # keep the ref while the loop is still draining past the
            # timeout — start() checks it to refuse a second loop
            if self._thread is thread and not (
                thread is not None and thread.is_alive()
            ):
                self._thread = None
        self._pool.close()  # withdraw from the free-pages gauge
        store = self._swap_store
        if store is not None:
            # segments of still-unanswered requests WITH a cross-restart
            # identity are parked for spill(), not dropped: the fleet
            # redrives such a request (same trace id) into the restarted
            # engine, and a parked segment turns that redrive into a
            # swap-in resume instead of a full prefill recompute.
            # Unkeyed or answered sequences drop as before.
            for r in list(self._swap):
                if r.trace_id:
                    self._swap_parked[str(r.trace_id)] = self._swap.pop(r)
                else:
                    self._drop_swap(r)
            # restored-but-never-redriven segments already live in this
            # store: park them too, so chained restarts keep them
            self._swap_parked.update(self._swap_restored)
            self._swap_restored.clear()
            if not self._swap_parked:
                self._swap_store = None
                store.close()  # deletes the root if the engine made it
            # else: the store stays open — spill() reads the parked
            # segments out of it (and then closes it), or a subsequent
            # start() reuses it
        # TFG113 evidence is scoped to RUNNING endpoints: a stopped
        # engine's config can no longer be fixed, so its findings are
        # withdrawn (lint_plan reads the live evidence each call)
        kept = [e for e in _PREFIX_INELIGIBLE
                if e.get("endpoint") != self.name]
        _PREFIX_INELIGIBLE.clear()
        _PREFIX_INELIGIBLE.extend(kept)
        for key in [k for k in _PREFIX_INELIGIBLE_SEEN
                    if k[0] == self.name]:
            _PREFIX_INELIGIBLE_SEEN.discard(key)
        _flight.record(
            "serving.decode.stop", endpoint=self.name, drain=drain,
        )

    def spill(self, store) -> Dict[str, object]:
        """Whole-engine KV snapshot (call after ``stop()``): the pool's
        whole-pool spill PLUS every parked per-sequence swap segment,
        folded into one snapshot dict — the PR 18 follow-up that stops
        swap segments dying with the engine. Hand the snapshot to a
        fresh engine's :meth:`restore` and redrive the pending requests
        (same trace ids): each resumes from its swapped pages through
        the normal swap-in path, bit-identically, with no prefill
        recompute. The engine's own swap store is emptied and closed
        (the segments now live in ``store``)."""
        with self._lock:
            if self._running or self._starting:
                raise ServingError(
                    f"decode engine {self.name!r}: spill() requires a "
                    "stopped engine (stop() first — a live loop would "
                    "race the snapshot)"
                )
            parked = dict(self._swap_parked)
        snap = self._pool.spill(
            store, swaps=parked, swap_store=self._swap_store,
        )
        swap_store = self._swap_store
        if swap_store is not None:
            for entry in parked.values():
                try:
                    swap_store.drop(entry["ref"])
                except Exception:  # pragma: no cover - already dropped
                    pass
            self._swap_parked.clear()
            self._swap_store = None
            swap_store.close()
        _flight.record(
            "serving.decode.spill", endpoint=self.name,
            swapped=len(snap.get("swapped", {})),
        )
        return snap

    def restore(self, store, snapshot: Dict[str, object]) -> int:
        """Adopt a :meth:`spill` snapshot's host-swapped sequences into
        this engine: segments are re-homed into the engine's swap store
        and parked by trace id; when the fleet redrives a pending
        request (same trace id), it resumes from its pages through the
        warmed swap-in executables instead of recomputing its prefill.
        Pool page state is NOT restored — a fresh engine owns a fresh
        pool, and swapped sequences hold no pages by construction.
        Returns the number of sequences adopted (corrupt segments are
        skipped with the store's counted quarantine; those requests
        degrade to a plain fresh decode on redrive)."""
        if not self._kv_swap:
            return 0
        if self._swap_store is None:
            from ..blockstore import BlockStore

            self._swap_store = BlockStore(
                root=self.config.swap_dir, budget_bytes=0,
            )
        manifest = self._pool.adopt_swapped(
            store, snapshot, self._swap_store
        )
        with self._lock:
            self._swap_restored.update(manifest)
        _flight.record(
            "serving.decode.restore", endpoint=self.name,
            adopted=len(manifest),
            offered=len(snapshot.get("swapped", {})),
        )
        return len(manifest)

    def _adopt_restored(self, req: "_Request") -> Optional[Dict]:
        """Move a restored swap snapshot onto a redriven request (same
        trace id), keeping the recompute-replay data beside it — the
        counted fallback if the segment comes back corrupt, exactly as
        :meth:`_preempt` does for a live preemption."""
        if not self._swap_restored or not req.trace_id:
            return None
        snap = self._swap_restored.pop(str(req.trace_id), None)
        if snap is None:
            return None
        self._swap[req] = snap
        self._resume[req] = (
            list(snap["generated"]) + list(snap["replay"] or ())
        )
        return snap

    # -- request path -------------------------------------------------------

    def validate_feeds(self, feeds) -> Dict[str, object]:
        """Normalize one decode request: ``{"prompt": 1-D int tokens
        (or [1, plen]), "max_new_tokens": optional int}``. Length bounds
        reject as ``too_large`` (the pool could never hold the
        horizon), malformed feeds as :class:`ValidationError`."""
        if not isinstance(feeds, dict) or "prompt" not in feeds:
            raise ValidationError(
                f"decode endpoint {self.name!r}: feeds must be a dict "
                "with a 'prompt' key (int token ids)"
            )
        extra = set(feeds) - {"prompt", "max_new_tokens"}
        if extra:
            raise ValidationError(
                f"decode endpoint {self.name!r}: unexpected feed(s) "
                f"{sorted(extra)}; accepted: prompt, max_new_tokens"
            )
        try:
            prompt = np.asarray(feeds["prompt"], dtype=np.int32)
        except (TypeError, ValueError) as e:
            raise ValidationError(
                f"decode endpoint {self.name!r}: prompt does not "
                f"convert to int32 tokens: {e}"
            ) from None
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValidationError(
                f"decode endpoint {self.name!r}: prompt must be a "
                f"non-empty 1-D token vector (or [1, plen]), got shape "
                f"{prompt.shape}"
            )
        vocab = int(self.cfg.vocab_size)
        if prompt.min() < 0 or prompt.max() >= vocab:
            raise ValidationError(
                f"decode endpoint {self.name!r}: prompt tokens must be "
                f"in [0, {vocab})"
            )
        new = feeds.get("max_new_tokens", self.config.max_new_tokens)
        try:
            new = int(new)
        except (TypeError, ValueError):
            raise ValidationError(
                f"decode endpoint {self.name!r}: max_new_tokens must "
                f"be an int, got {feeds['max_new_tokens']!r}"
            ) from None
        if new < 1 or new > self.config.max_new_tokens:
            raise ValidationError(
                f"decode endpoint {self.name!r}: max_new_tokens={new} "
                f"outside [1, {self.config.max_new_tokens}]"
            )
        plen = int(prompt.shape[0])
        if plen > self.config.max_prompt_len:
            m.rejected("too_large").inc()
            raise RejectedError(
                f"decode endpoint {self.name!r}: prompt of {plen} "
                f"tokens exceeds max_prompt_len="
                f"{self.config.max_prompt_len} — split or raise the "
                "engine's DecodeConfig",
                reason="too_large",
            )
        return {"prompt": prompt, "new": new}

    def submit(self, feeds,
               deadline_s: Optional[float] = None) -> ResultFuture:
        """Admit one decode request; the future resolves to
        ``{"tokens": int32 [1, max_new_tokens]}`` when its LAST token is
        generated (streaming-final semantics). Raises
        :class:`RejectedError` on shed/closed/oversize, the deadline
        covers queue + slot wait."""
        norm = self.validate_feeds(feeds)
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}) — the same "
                "contract as RetryPolicy.deadline_s"
            )
        return self._admission.offer(norm, 1, deadline_s)

    def call(self, feeds, deadline_s: Optional[float] = None,
             timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        return self.submit(feeds, deadline_s).result(timeout)

    # -- the engine loop ----------------------------------------------------

    def _loop(self) -> None:
        try:
            self._loop_body()
        except BaseException as e:  # pragma: no cover - crash guard
            logger.exception("decode engine %r loop died", self.name)
            _flight.record(
                "serving.decode.error", endpoint=self.name,
                error=type(e).__name__, message=str(e),
            )
            self._fail_all(ServingError(
                f"decode engine {self.name!r} failed: "
                f"{type(e).__name__}: {e}"
            ))

    def _loop_body(self) -> None:
        cfg = self.config
        while True:
            with self._lock:
                stopping, drain = self._stopping, self._drain
            if stopping and not drain:
                self._fail_all(ServingError(
                    f"decode engine {self.name!r} stopped without "
                    "drain; running sequences abandoned"
                ))
                return
            self._purge_resume()
            free = [i for i, s in enumerate(self._slots) if s is None]
            if free:
                for req in self._admission.poll(
                    len(free), can_take=self._admit_budget()
                ):
                    self._join(req)
            if any(s is not None for s in self._slots):
                self._decode_step()
                continue
            # idle: nothing running
            if stopping and self._admission.queued_rows == 0:
                return
            if self._admission.queued_rows > 0:
                # queued but unadmittable (pool pages held elsewhere):
                # a bounded nap, not a hot spin — wait_for_work returns
                # immediately on a non-empty queue, and the expirer
                # thread (not this loop) owns deadline expiry
                time.sleep(0.005)
            else:
                self._admission.wait_for_work(0.02)

    def _admit_budget(self):
        """A fresh admission predicate for ONE poll: each accepted
        request claims its prompt pages from the snapshot budget, so a
        multi-request poll can never overcommit the pool (the joins run
        after the poll returns). The budget is ``num_allocatable`` —
        free pages plus reclaimable refcount-0 shared pages — and a
        host-swapped request claims its SNAPSHOT's page count (it may
        hold pages past its prompt), not its prompt estimate."""
        budget = [self._pool.num_allocatable]

        def can_take(req: _Request) -> bool:
            snap = self._swap.get(req)
            if snap is None:
                # a redriven request adopting a restored swap segment
                # claims its SNAPSHOT pages too (engine-restart resume)
                snap = self._adopt_restored(req)
            if snap is not None:
                need = int(snap["pages"])
            else:
                need = self._pool.pages_needed(
                    int(req.feeds["prompt"].shape[0])
                )
            if need > budget[0]:
                return False
            budget[0] -= need
            return True

        return can_take

    def _purge_resume(self) -> None:
        # a preempted request can expire (or be abandoned) while
        # requeued — its future resolves in the expirer; drop its
        # replay state (and swap segment) so neither can grow
        # unboundedly
        if self._resume:
            dead = [r for r in self._resume if r.future.done()]
            for r in dead:
                del self._resume[r]
        if self._swap:
            for r in [r for r in self._swap if r.future.done()]:
                self._drop_swap(r)

    def _drop_swap(self, req: _Request) -> None:
        snap = self._swap.pop(req, None)
        if snap is not None and self._swap_store is not None:
            try:
                self._swap_store.drop(snap["ref"])
            except Exception:  # pragma: no cover - already dropped
                pass

    def _prefill_bucket(self, plen: int) -> int:
        for b in self._prefill_buckets:
            if b >= plen:
                return b
        raise AssertionError(  # pragma: no cover - validated at submit
            f"prompt of {plen} tokens above the warmed prefill ladder "
            f"{self._prefill_buckets}"
        )

    def _note_prefix_ineligible(self, reason: str, plen: int) -> None:
        key = (self.name, reason)
        if key in _PREFIX_INELIGIBLE_SEEN:
            return
        _PREFIX_INELIGIBLE_SEEN.add(key)
        _PREFIX_INELIGIBLE.append({
            "endpoint": self.name, "reason": reason,
            "prompt_len": int(plen),
            "page_size": int(self.config.page_size),
        })

    def _prefill_seq(self, seq: int, prompt: np.ndarray, plen: int,
                     resumed: bool) -> Tuple[int, int]:
        """Write the prompt's KV for a fresh sequence and produce its
        first token through the cheapest eligible path: shared-prefix
        suffix prefill, copy-on-extend, or cold full prefill. Returns
        ``(first_token, shared_pages_referenced)``."""
        hit_pages: List[int] = []
        covered = 0
        cow = None
        if not self._prefix_cache:
            # evidence only on an OBSERVED repeat: a prompt whose first
            # page was already prefilled by an earlier fresh request is
            # work the cache would have shared — an engine that never
            # sees overlap has nothing to gain and records nothing
            if not resumed and plen > self.config.page_size:
                fp = prompt[:self.config.page_size].tobytes()
                if fp in self._seen_first_pages:
                    self._note_prefix_ineligible("store_unarmed", plen)
                elif len(self._seen_first_pages) < 512:
                    self._seen_first_pages.add(fp)
        elif resumed:
            # a replay-resumed join must reproduce its recorded tokens
            # against the page state that existed at first admission;
            # routing it through cache pages published since would
            # change accounting mid-replay — ineligible by design
            self._note_prefix_ineligible("sampling_state_mismatch", plen)
        else:
            hit_pages, covered, cow, _r = self._pool.prefix_match(prompt)
            if not hit_pages and cow is None:
                if plen <= self.config.page_size:
                    # below one full page nothing can ever be published
                    # or matched at page granularity
                    self._note_prefix_ineligible("page_misalignment", plen)
                m.PREFIX_MISSES.inc()
                self._prefix_misses += 1
        if hit_pages:
            self._pool.prefix_acquire(seq, hit_pages)
        if hit_pages or cow is not None:
            m.PREFIX_HITS.inc()
            self._prefix_hits += 1
        if cow is not None:
            # the whole remaining tail is resident in a published page:
            # copy it (never write a shared page), then teacher-force
            # only the final prompt token through the solo decode step
            # — it rewrites KV the copy already holds (deterministic,
            # identical) and yields the first-token logits
            dst = self._pool.copy_on_extend(seq, cow)
            self._pool.columns = self._copy_page(
                self._pool.columns, np.int32(cow), np.int32(dst)
            )
            sb = self._slot_buckets[0]
            maxp = self._pool.max_pages_per_seq
            tokens = np.zeros(sb, np.int32)
            pos = np.zeros(sb, np.int32)
            tables = np.zeros((sb, maxp), np.int32)
            tokens[0] = int(prompt[plen - 1])
            pos[0] = plen - 1
            tables[0] = self._pool.table(seq)
            cols, nxt = self._run_step(
                self.params, self._pool.columns, tokens, pos, tables
            )
            self._pool.columns = cols
            first = int(np.asarray(nxt)[0])
        elif hit_pages:
            # matched pages cover [0, covered); prefill only the suffix
            # through the gather-attending executable (its rows see the
            # shared pages through the sequence's table)
            self._pool.alloc(
                seq, self._pool.pages_needed(plen) - len(hit_pages)
            )
            tlen = plen - covered
            tb = self._prefill_bucket(tlen)
            padded = np.zeros(tb, np.int32)
            padded[:tlen] = prompt[covered:]
            cols, fd = self._suffix_prefill(
                self.params, self._pool.columns, padded,
                np.int32(covered), np.int32(tlen),
                self._pool.table(seq),
            )
            self._pool.columns = cols
            first = int(fd)
        else:
            self._pool.alloc(seq, self._pool.pages_needed(plen))
            tb = self._prefill_bucket(plen)
            padded = np.zeros(tb, np.int32)
            padded[:plen] = prompt
            cols, fd = self._prefill(
                self.params, self._pool.columns, padded,
                np.int32(plen), self._pool.table(seq),
            )
            self._pool.columns = cols
            first = int(fd)
        m.DECODE_STEPS["prefill"].inc()
        if self._prefix_cache and not resumed:
            # publish this prompt's freshly written FULL pages so later
            # requests can share them (no-op on total overlap; stops at
            # chain-key collisions with another lineage)
            self._pool.publish_prefix(seq, prompt)
        if hit_pages or cow is not None:
            _flight.record(
                "serving.decode.prefix_hit", endpoint=self.name,
                seq=seq, prompt_len=plen,
                shared_pages=len(hit_pages), covered_tokens=covered,
                copy_on_extend=cow is not None,
            )
        return first, len(hit_pages)

    def _join(self, req: _Request) -> None:
        now = time.perf_counter()
        if req.deadline is not None and req.deadline <= now:
            # lost the race with the expirer between poll and here
            m.DEADLINE_EXPIRED.inc()
            req.future._fail(DeadlineExceededError(
                f"request to {self.name!r} expired after "
                f"{now - req.t_submit:.4f}s waiting for a decode slot"
            ))
            self._resume.pop(req, None)
            self._drop_swap(req)
            return
        if self._swap_store is not None and req in self._swap:
            if self._swap_in(req, self._swap.pop(req), now):
                return
            # counted fallback: the replay data kept alongside the
            # snapshot resumes it through the recompute path below
        prompt = req.feeds["prompt"]
        plen = int(prompt.shape[0])
        seq = self._next_seq
        self._next_seq += 1
        replay = self._resume.pop(req, None)
        first, prefix_pages = self._prefill_seq(
            seq, prompt, plen, resumed=bool(replay)
        )
        self._join_counter += 1
        s = _Seq(req, seq, prompt, int(req.feeds["new"]),
                 self._join_counter)
        tok = first
        if replay:
            s.replay = collections.deque(replay)
            expect = s.replay.popleft()
            if tok != expect:
                self._bit_identity_violation(s, tok, expect)
                return
            if not s.replay:
                s.replay = None
        else:
            m.DECODE_TTFT.observe(time.perf_counter() - req.t_submit)
            m.DECODE_TOKENS.inc()
        s.generated.append(tok)
        idx = self._slots.index(None)
        self._slots[idx] = s
        # delta, not set(): several engines share the process-wide
        # occupancy gauge (the free-pages twin lives in PagedKVPool)
        m.DECODE_SLOTS.inc()
        _flight.record(
            "serving.decode.join", endpoint=self.name, seq=seq,
            prompt_len=plen, new_tokens=s.want,
            resumed=bool(replay), prefix_pages=prefix_pages,
            waited_s=round(now - req.t_submit, 6),
        )
        if _events.TRACER.enabled:
            args = {"endpoint": self.name, "seq": seq,
                    "prompt_len": plen, "resumed": bool(replay)}
            if req.trace_id:
                args["request_id"] = req.trace_id
            _events.TRACER.emit_complete(
                "decode.join", now, time.perf_counter() - now,
                args=args, cat="serving",
            )
        if len(s.generated) >= s.want:
            self._finish(s)

    def _swap_in(self, req: _Request, snap: Dict[str, object],
                 now: float) -> bool:
        """Restore a host-swapped sequence's pages bit-identically and
        put it straight back into a slot — no prefill, no replay, no
        recompute. Returns False on ANY store or pool problem (counted
        as ``tftpu_kvswap_fallback_total``; the caller's replay path
        still resumes the request — a swap problem never loses one)."""
        seq = self._next_seq
        self._next_seq += 1
        try:
            pages, block = self._pool.swap_in_seq(
                self._swap_store, snap, seq
            )
        except Exception as e:
            # a corrupt segment was already quarantined + counted by
            # the store; drop the ref if it survived, count the
            # fallback, and let the replay join take over
            try:
                self._swap_store.drop(snap["ref"])
            except Exception:
                pass
            m.KVSWAP_FALLBACKS.inc()
            self._swap_fallbacks += 1
            logger.warning(
                "decode engine %r: swap-in failed (%s: %s); falling "
                "back to recompute-replay", self.name,
                type(e).__name__, e,
            )
            _flight.record(
                "serving.decode.swap_fallback", endpoint=self.name,
                error=type(e).__name__, message=str(e)[:200],
            )
            return False
        maxp = self._pool.max_pages_per_seq
        npg = len(pages)
        idx = np.zeros(maxp, np.int32)
        idx[:npg] = pages
        payload = []
        for name in ("k", "v", "k_scale", "v_scale"):
            arr = np.asarray(block[name])
            fullp = np.zeros((maxp,) + arr.shape[1:], arr.dtype)
            fullp[:npg] = arr
            payload.append(fullp)
        # padding rows scatter zeros into the null page — garbage by
        # contract; one fixed-shape dispatch, warmed at start
        self._pool.columns = self._restore(
            self._pool.columns, idx, *payload
        )
        self._join_counter += 1
        s = _Seq(req, seq, req.feeds["prompt"],
                 int(req.feeds["new"]), self._join_counter)
        s.pos = int(snap["pos"])
        s.generated = list(snap["generated"])
        s.replay = (collections.deque(snap["replay"])
                    if snap["replay"] else None)
        self._resume.pop(req, None)
        self._slots[self._slots.index(None)] = s
        m.DECODE_SLOTS.inc()
        m.KVSWAP_RESUMES.inc()
        self._swap_resumes += 1
        _flight.record(
            "serving.decode.swap_in", endpoint=self.name, seq=seq,
            pages=npg, tokens_done=len(s.generated),
            waited_s=round(now - req.t_submit, 6),
        )
        if _events.TRACER.enabled:
            args = {"endpoint": self.name, "seq": seq,
                    "swap_resumed": True}
            if req.trace_id:
                args["request_id"] = req.trace_id
            _events.TRACER.emit_complete(
                "decode.join", now, time.perf_counter() - now,
                args=args, cat="serving",
            )
        if len(s.generated) >= s.want:
            self._finish(s)
        return True

    def _active(self) -> List[_Seq]:
        return [s for s in self._slots if s is not None]

    def _decode_step(self) -> None:
        # page faults first, oldest slot first: a slot whose next write
        # position crosses into an unallocated page must get one, by
        # preemption if the pool is dry. The victim is always the
        # YOUNGEST running sequence (possibly the faulting slot itself)
        # — the oldest is never evicted, and the pool floor (one full
        # horizon) guarantees it can always finish: forward progress is
        # structural, preemption cannot livelock.
        for s in sorted(self._active(), key=lambda x: x.joined):
            if s not in self._slots:
                continue  # preempted by an earlier fault in this pass
            need = s.pos // self._pool.page_size
            if need < len(self._pool.seq_pages(s.seq)):
                continue
            preempted_self = False
            while self._pool.num_allocatable < 1:
                victim = max(self._active(), key=lambda x: x.joined)
                self._preempt(victim)
                if victim is s:
                    preempted_self = True
                    break
            if preempted_self:
                continue
            try:
                self._pool.alloc(s.seq, 1)
            except PoolExhaustedError:  # pragma: no cover - guarded above
                self._preempt(s)
        active = self._active()
        if not active:
            return
        n = len(active)
        sb = next(b for b in self._slot_buckets if b >= n)
        maxp = self._pool.max_pages_per_seq
        tokens = np.zeros(sb, np.int32)
        pos = np.zeros(sb, np.int32)
        tables = np.zeros((sb, maxp), np.int32)
        for row, s in enumerate(active):
            tokens[row] = s.generated[-1]
            pos[row] = s.pos
            tables[row] = self._pool.table(s.seq)
        t_step = time.perf_counter()
        cols, nxt = self._run_step(
            self.params, self._pool.columns, tokens, pos, tables
        )
        self._pool.columns = cols
        nxt = np.asarray(nxt)
        m.DECODE_STEPS["decode"].inc()
        if _events.TRACER.enabled:
            args = {"endpoint": self.name, "slots": n}
            rids = [s.req.trace_id for s in active if s.req.trace_id]
            if rids:
                args["request_ids"] = rids[:16]
            _events.TRACER.emit_complete(
                "decode.step", t_step, time.perf_counter() - t_step,
                args=args, cat="serving",
            )
        for row, s in enumerate(active):
            s.pos += 1
            tok = int(nxt[row])
            if s.replay:
                expect = s.replay.popleft()
                if tok != expect:
                    self._bit_identity_violation(s, tok, expect)
                    continue
                if not s.replay:
                    s.replay = None
                tok = expect
            else:
                m.DECODE_TOKENS.inc()
            s.generated.append(tok)
            if len(s.generated) >= s.want:
                self._finish(s)

    def _slot_of(self, s: _Seq) -> int:
        return self._slots.index(s)

    def _swap_out(self, s: _Seq) -> Optional[Dict[str, object]]:
        """Extract the sequence's pages (one fixed-shape gather, warmed)
        and publish them to the swap store's CRC-stamped disk segment.
        Returns the snapshot, or None if the store write failed — the
        caller falls back to plain eviction + recompute-replay, so a
        swap problem can never lose a request."""
        pages = self._pool.seq_pages(s.seq)
        maxp = self._pool.max_pages_per_seq
        idx = np.zeros(maxp, np.int32)
        idx[:len(pages)] = pages
        ex = self._extract(self._pool.columns, idx)
        block = {
            name: np.ascontiguousarray(np.asarray(col)[:len(pages)])
            for name, col in ex.items()
        }
        try:
            snap = self._pool.swap_out_seq(
                self._swap_store, s.seq, block
            )
        except Exception as e:
            logger.warning(
                "decode engine %r: swap-out failed (%s: %s); evicting "
                "with recompute-replay resume", self.name,
                type(e).__name__, e,
            )
            return None
        snap["pos"] = s.pos
        snap["generated"] = list(s.generated)
        snap["replay"] = list(s.replay or ())
        m.KVSWAP_OUTS.inc()
        m.KVSWAP_BYTES.inc(int(snap["ref"].nbytes))
        self._swap_outs += 1
        _flight.record(
            "serving.decode.swap_out", endpoint=self.name, seq=s.seq,
            pages=int(snap["pages"]), bytes=int(snap["ref"].nbytes),
            tokens_done=len(s.generated),
        )
        return snap

    def _preempt(self, s: _Seq) -> None:
        self._slots[self._slot_of(s)] = None
        m.DECODE_SLOTS.dec()
        snap = (self._swap_out(s)
                if self._swap_store is not None else None)
        freed = (int(snap["freed"]) if snap is not None
                 else self._pool.free_seq(s.seq))
        m.DECODE_PREEMPTIONS.inc()
        m.DECODE_EVICTIONS.inc(freed)
        _flight.record(
            "serving.decode.preempt", endpoint=self.name, seq=s.seq,
            tokens_done=len(s.generated), pages_evicted=freed,
            swapped=snap is not None,
        )
        # requeue at the HEAD with the generated prefix intact: on
        # rejoin, a swap snapshot restores the pages outright, and the
        # recompute-replay data is ALWAYS kept beside it — prefill +
        # teacher-forced replay through the same executables is the
        # counted fallback if the segment comes back corrupt. A
        # sequence preempted MID-replay keeps its unreplayed suffix
        # too — dropping it would re-count those tokens as fresh and
        # silently skip their bit-identity check
        self._resume[s.req] = list(s.generated) + list(s.replay or ())
        if snap is not None:
            self._swap[s.req] = snap
        if not self._admission.requeue_front(s.req):
            self._resume.pop(s.req, None)
            self._drop_swap(s.req)

    def _finish(self, s: _Seq) -> None:
        self._slots[self._slot_of(s)] = None
        m.DECODE_SLOTS.dec()
        self._pool.free_seq(s.seq)
        out = np.asarray(s.generated[:s.want], np.int32)[None, :]
        done = time.perf_counter()
        m.REQUEST_LATENCY.observe(done - s.req.t_submit)
        s.req.future._set({"tokens": out})
        _flight.record(
            "serving.decode.finish", endpoint=self.name, seq=s.seq,
            tokens=int(out.shape[1]),
            seconds=round(done - s.req.t_submit, 6),
        )
        if _events.TRACER.enabled:
            args = {"endpoint": self.name, "seq": s.seq,
                    "tokens": int(out.shape[1])}
            if s.req.trace_id:
                args["request_id"] = s.req.trace_id
            _events.TRACER.emit_complete(
                "decode.finish", s.req.t_submit, done - s.req.t_submit,
                args=args, cat="serving",
            )

    def _bit_identity_violation(self, s: _Seq, got: int,
                                expect: int) -> None:
        """A resumed sequence diverged from its recorded prefix — a
        determinism bug, never load. Fail THIS request loudly (the
        engine keeps serving); silently continuing would hand the
        client a sequence that contradicts the preemption contract.
        Callable both mid-join (slot not yet assigned) and mid-step."""
        if s in self._slots:
            self._slots[self._slot_of(s)] = None
            m.DECODE_SLOTS.dec()
        self._pool.free_seq(s.seq)
        m.DISPATCH_ERRORS.inc()
        _flight.record(
            "serving.decode.replay_divergence", endpoint=self.name,
            seq=s.seq, got=got, expected=expect,
            at_token=len(s.generated),
        )
        s.req.future._fail(ServingError(
            f"decode engine {self.name!r}: resumed sequence diverged "
            f"from its pre-preemption prefix (got token {got}, "
            f"recorded {expect} at index {len(s.generated)}) — "
            "determinism bug, please report"
        ))

    def _fail_all(self, exc: BaseException) -> None:
        for i, s in enumerate(self._slots):
            if s is not None:
                self._slots[i] = None
                m.DECODE_SLOTS.dec()
                self._pool.free_seq(s.seq)
                s.req.future._fail(exc)
        self._admission.close(drain=False)
