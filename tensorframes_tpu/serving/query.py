"""Registered relational query endpoints (ISSUE 20 / ROADMAP #3).

``Server.register_query(name, source, build)`` turns a lazy relational
pipeline into a served product: ``build`` is a callable taking the
source frame and returning a lazy verb chain (map → join → aggregate),
and every ``submit(name, {})`` answers with the pipeline's current
result table over the source's CURRENT contents — a growing
``scan_csv``/``scan_parquet`` directory or a static frame.

Three layers keep a recurring dashboard-style query O(new data)
instead of O(table):

* **Result cache** — keyed by (plan fingerprint, input-partition
  content digest): :func:`plan.stats.chain_fingerprint` names WHAT
  computes, :func:`compilecache.fingerprint.content_digest` over the
  chunk-arrival manifest names WHAT it computes over. A repeat query
  is a memo/store lookup — no chunk read, no plan execution, no
  dispatch, hence zero steady-state compiles by construction. The
  persistent half lives in a :class:`blockstore.ResultStore` under
  ``<TFTPU_COMPILE_CACHE>/results`` so a RESTARTED process hits too.
* **Incremental aggregate maintenance** — when the chain is a
  scan-rooted map/filter pipeline ending in an algebraic aggregate
  whose every (op, dtype) passes
  :func:`plan.rules.incremental_fold_safe` and whose group keys pass
  through from the source, the endpoint maintains one aggregate
  partial table PER CHUNK (keyed by the chunk's stat signature) and
  answers by folding them (:func:`plan.lower.fold_partial_tables` —
  bit-identical to full recompute by exact associativity, not by
  tolerance). An appended part re-reads and re-executes ONLY itself; a
  rewritten part invalidates only its own partial.
* **Counted degradation** — anything outside that contract (host
  callbacks, non-algebraic fetches, joins, computed keys, float-sum /
  mean accumulation, eager builders) degrades to counted full
  recompute with a named reason: the ``tftpu_result_cache_recomputes_
  total{reason=}`` series, a TFG114 diagnostic via
  :func:`query_cache_events`, and ``Server.stats()`` rows. Degraded
  endpoints still answer correctly — they just pay O(table).

Result rows are served in :func:`plan.lower.canonical_table_order`
(sorted by group keys) so a folded refresh, a full recompute, and a
``TFTPU_FUSION=0`` oracle run are byte-comparable. Under
``TFTPU_FUSION=0`` the plan chain never records (the verbs execute
eagerly — that IS the oracle mode), so persistent caching and
incremental maintenance disarm silently (no TFG114 noise: the decline
is operator-chosen, not fixable) and only the in-process memo serves
repeats.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability import flight as _flight
from ..observability.metrics import Histogram
from ..observability.latency import LATENCY_BUCKETS
from ..utils import get_logger
from ..validation import ValidationError
from .batcher import RejectedError, ResultFuture
from . import metrics as m

logger = get_logger(__name__)

__all__ = [
    "QuerySource", "QueryEndpoint", "query_cache_events",
    "QUERY_DECLINE_REASONS",
]

#: Closed set of TFG114 decline reasons (analysis/rules.py maps each to
#: an actionable fix; the taxonomy is part of the diagnostic contract).
QUERY_DECLINE_REASONS: Tuple[str, ...] = (
    "host_callback", "non_algebraic", "eager", "join", "computed_key",
    "reduce_mean", "float_accumulation", "no_terminal_aggregate",
)


@dataclasses.dataclass(frozen=True)
class QuerySource:
    """Where a registered query reads from.

    ``path`` + ``kind`` ('csv' | 'parquet') names a growing directory
    (or explicit part list) scanned per request through
    :func:`io.part_manifest`; ``frame`` registers a static in-memory
    frame instead (content-digested via
    :func:`compilecache.fingerprint.frame_content_digest`). CSV column
    types are pinned from the first part with rows (pass ``dtypes`` to
    pin them yourself — the scan_csv contract)."""

    path: Optional[str] = None
    kind: str = "csv"
    frame: Optional[object] = None
    delimiter: str = ","
    dtypes: Optional[Dict[str, str]] = None

    def __post_init__(self):
        if self.frame is not None:
            if self.path is not None:
                raise ValueError(
                    "QuerySource takes path OR frame, not both"
                )
            return
        if self.path is None:
            raise ValueError("QuerySource needs a path or a frame")
        if self.kind not in ("csv", "parquet"):
            raise ValueError(
                f"QuerySource kind must be 'csv' or 'parquet', "
                f"got {self.kind!r}"
            )


# ---------------------------------------------------------------------------
# TFG114 evidence: registered endpoints whose plan declined caching or
# incremental maintenance, with the blocking stage named. Module-level
# like decode.prefix_cache_events (the TFG113 pattern): analyzer.
# lint_plan imports the accessor; registration appends, deduped per
# (endpoint, mode, reason); a rolled-back or re-registered endpoint
# withdraws its rows so stale evidence never outlives the endpoint.
# ---------------------------------------------------------------------------

_QUERY_EVENTS: List[dict] = []
_QUERY_SEEN: set = set()
_EVENTS_LOCK = threading.Lock()


def query_cache_events() -> List[dict]:
    """TFG114 evidence rows: ``{"endpoint", "mode", "reason",
    "detail"}`` — mode 'cache' means the result cache disarmed (every
    request recomputes), mode 'incremental' means refreshes pay full
    recompute while repeats still cache."""
    with _EVENTS_LOCK:
        return [dict(e) for e in _QUERY_EVENTS]


def _record_event(endpoint: str, mode: str, reason: str,
                  detail: str) -> None:
    assert reason in QUERY_DECLINE_REASONS, reason
    key = (endpoint, mode, reason)
    with _EVENTS_LOCK:
        if key in _QUERY_SEEN:
            return
        _QUERY_SEEN.add(key)
        _QUERY_EVENTS.append({
            "endpoint": endpoint, "mode": mode, "reason": reason,
            "detail": detail,
        })


def _withdraw_events(endpoint: str) -> None:
    with _EVENTS_LOCK:
        _QUERY_EVENTS[:] = [
            e for e in _QUERY_EVENTS if e["endpoint"] != endpoint
        ]
        _QUERY_SEEN.difference_update(
            {k for k in _QUERY_SEEN if k[0] == endpoint}
        )


def _result_key(fp: str, digest: str) -> str:
    return f"{fp}-r{digest}"


def _partial_key(fp: str, sig: str) -> str:
    return f"{fp}-p{sig}"


class QueryEndpoint:
    """One registered relational pipeline, served.

    Requests carry NO feeds (``{}``/None — the query's input is the
    source's current contents); execution runs synchronously under the
    endpoint lock in the submitting thread, so a cache hit's latency
    IS the lookup. Exposes the batcher-compatible ``counters()`` shape
    so ``Server.stats()`` tallies it like any endpoint, plus
    ``cache_stats()`` for the result-cache rows."""

    def __init__(self, name: str, source: QuerySource,
                 build: Callable[[object], object]):
        self.name = name
        self.source = source
        self.build = build
        self._lock = threading.RLock()
        self._open = False
        # batcher-compatible admission counters (per-endpoint, stats())
        self._admitted_requests = 0
        self._admitted_rows = 0
        self._rejected = {r: 0 for r in m.REJECT_REASONS}
        self._latency = Histogram(
            "serving_endpoint_latency_seconds",
            f"request latency for query endpoint {name!r}",
            (), threading.Lock(), buckets=LATENCY_BUCKETS,
        )
        # result-cache counters (per-endpoint mirrors of the
        # process-wide tftpu_result_cache_* registry series)
        self._hits = 0
        self._misses = 0
        self._invalidations = 0
        self._bytes = 0
        self._chunks_folded = 0
        self._chunks_executed = 0
        self._recomputes = {r: 0 for r in m.RECOMPUTE_REASONS}
        # cache state
        self._memo_digest: Optional[str] = None
        self._memo_table: Optional[Dict[str, np.ndarray]] = None
        self._last_manifest: Optional[List[Tuple[str, str]]] = None
        self._mem_partials: Dict[str, Dict[str, np.ndarray]] = {}
        self._store = None
        self._store_root: Optional[str] = None
        # plan probe state (filled by _probe)
        self._fp: Optional[str] = None
        self._cache_reason: Optional[Tuple[str, str]] = None
        self._inc_reason: Optional[Tuple[str, str]] = None
        self._agg_keys: Tuple[str, ...] = ()
        self._agg_ops: Tuple[Tuple[str, str], ...] = ()
        self._result_schema = None
        self._csv_dtypes: Optional[Dict[str, str]] = dict(
            source.dtypes) if source.dtypes else None
        self._probe()

    # -- source scanning ----------------------------------------------------

    def _manifest(self) -> List[Tuple[str, str]]:
        """Current chunk-arrival manifest: ``[(path, signature)]``."""
        if self.source.frame is not None:
            from ..compilecache.fingerprint import frame_content_digest

            return [("<frame>", frame_content_digest(self.source.frame))]
        from ..io import part_manifest

        return part_manifest(self.source.path, kind=self.source.kind)

    def _chunk_frame(self, path: str):
        if self.source.frame is not None:
            return self.source.frame
        from ..io import part_frame

        return part_frame(
            path, kind=self.source.kind,
            delimiter=self.source.delimiter, dtypes=self._csv_dtypes,
        )

    # -- plan probe ---------------------------------------------------------

    def _probe(self) -> None:
        """Fingerprint the pipeline and walk its eligibility ONCE, over
        the first chunk with rows: the chain signature is content-based
        (schema + node specs), so one chunk stands for the table."""
        manifest = self._manifest()
        probe = None
        for path, _ in manifest:
            f = self._chunk_frame(path)
            if f.num_rows > 0:
                probe = f
                break
        if probe is None:
            raise ValueError(
                f"query endpoint {self.name!r}: no part with rows under "
                f"{self.source.path!r} — register after the first data "
                "arrives (the probe pins CSV dtypes from it)"
            )
        if self.source.kind == "csv" and self._csv_dtypes is None:
            # pin types from the probe part, exactly like scan_csv: two
            # chunks of one table must never parse under different types
            self._csv_dtypes = {
                info.name: (info.dtype.name
                            if info.dtype.name in ("int64", "float64")
                            else "string")
                for info in probe.schema
            }
        result = self.build(probe)
        if result is None or not hasattr(result, "schema"):
            raise ValueError(
                f"query endpoint {self.name!r}: build must return a "
                f"frame, got {type(result).__name__}"
            )
        self._result_schema = result.schema
        self._inspect(result, probe)
        from ..plan import ir as plan_ir

        for mode, why in (("cache", self._cache_reason),
                          ("incremental", self._inc_reason)):
            # fusion-off is the operator-chosen oracle mode, not a
            # fixable plan property: no TFG114 evidence for it
            if why is not None and plan_ir.fusion_enabled():
                _record_event(self.name, mode, why[0], why[1])
        _flight.record(
            "serving.query_registered", endpoint=self.name,
            fp=self._fp, chunks=len(manifest),
            cache=self._cache_reason is None,
            incremental=self._inc_reason is None,
        )

    def _inspect(self, result, probe) -> None:
        from ..plan import ir as plan_ir
        from ..plan import stats as plan_stats
        from ..plan.rules import incremental_fold_safe

        node = getattr(result, "_plan", None)
        if node is None:
            unf = plan_ir.unfused_epilogues(result)
            if unf:
                why = ("non_algebraic",
                       f"aggregate epilogue stayed unfused: "
                       f"{unf[0].get('reason', 'non-algebraic fetches')}")
            else:
                why = ("eager",
                       "build returned a frame with no recorded plan "
                       "chain (already forced, or planning disabled)")
            self._cache_reason = self._inc_reason = why
            return
        src, nodes = plan_ir.resolve_chain(node)
        self._fp = plan_stats.chain_fingerprint(src, nodes)
        for n in nodes:
            if n.kind == "map" and plan_ir.program_has_callback(n.program):
                outs = ",".join(n.out_names)
                self._cache_reason = self._inc_reason = (
                    "host_callback",
                    f"map stage producing [{outs}] runs a host "
                    "callback — results are not a pure function of the "
                    "plan fingerprint, so neither cache level is sound",
                )
                return
        term = nodes[-1]
        if term.kind != "aggregate":
            self._inc_reason = (
                "no_terminal_aggregate",
                f"chain ends in {term.kind!r}, not a keyed algebraic "
                "aggregate — only aggregate partials fold across chunks",
            )
            return
        self._agg_keys = tuple(term.keys)
        self._agg_ops = tuple((o, op) for o, op, _ in (term.spec or ()))
        self._result_schema = term.schema
        joins = [n for n in nodes if n.kind == "join"]
        if joins:
            self._inc_reason = (
                "join",
                "the chain joins against another frame — per-chunk "
                "partials of a join-then-aggregate are not maintained "
                "(build-side changes would silently stale them)",
            )
            return
        map_outs = {o for n in nodes if n.kind == "map"
                    for o in n.out_names}
        computed = sorted(k for k in term.keys if k in map_outs)
        if computed:
            self._inc_reason = (
                "computed_key",
                f"group key(s) {computed} are computed by a map stage, "
                "not passed through from the scan — a chunk's key set "
                "is then not a pure function of the chunk",
            )
            return
        for o, op in self._agg_ops:
            dtype = term.schema[o].dtype.np_dtype
            if op == "reduce_mean":
                self._inc_reason = (
                    "reduce_mean",
                    f"fetch {o!r} is a mean — partials fold only as a "
                    "(sum, count) companion pair, which partial tables "
                    "do not carry yet; aggregate sum and count instead",
                )
                return
            if not incremental_fold_safe(op, dtype):
                self._inc_reason = (
                    "float_accumulation",
                    f"fetch {o!r} ({op} over "
                    f"{np.dtype(dtype).name}) reassociates across "
                    "chunks — the fold would not be bit-identical to "
                    "full recompute; cast to an integer dtype or accept "
                    "full recompute",
                )
                return

    # -- persistent store ---------------------------------------------------

    def _result_store(self):
        """The persistent store, armed only when caching is eligible
        AND a compile-cache dir is configured (the same opt-in that
        arms the AOT store and the plan-stats sidecar)."""
        if self._cache_reason is not None or self._fp is None:
            return None
        from ..config import get_config

        root = get_config().compilation_cache_dir
        if not root:
            return None
        root = os.path.join(root, "results")
        if self._store is None or self._store_root != root:
            from ..blockstore.resultstore import ResultStore

            self._store = ResultStore(root)
            self._store_root = root
        return self._store

    # -- execution ----------------------------------------------------------

    def _table_of(self, frame) -> Dict[str, np.ndarray]:
        return {
            name: frame.column_values(name)
            for name in frame.schema.names
        }

    def _empty_table(self) -> Dict[str, np.ndarray]:
        out = {}
        for info in self._result_schema:
            np_dtype = info.dtype.np_dtype
            out[info.name] = np.zeros(
                (0,),
                dtype=(object if np.dtype(np_dtype) == object
                       else np_dtype),
            )
        return out

    def _run_chunk(self, path: str) -> Dict[str, np.ndarray]:
        frame = self._chunk_frame(path)
        if frame.num_rows == 0:
            return self._empty_table()
        return self._table_of(self.build(frame))

    def _execute_full(self, manifest) -> Dict[str, np.ndarray]:
        """Full recompute: every chunk read, one pipeline execution
        over the concatenated table (the oracle path)."""
        from ..frame import frame_from_arrays

        frames = [self._chunk_frame(p) for p, _ in manifest]
        frames = [f for f in frames if f.num_rows > 0]
        if not frames:
            return self._empty_table()
        if len(frames) == 1:
            full = frames[0]
        else:
            cols: Dict[str, object] = {}
            for info in frames[0].schema:
                parts = [f.column_values(info.name) for f in frames]
                if any(p.dtype == object for p in parts):
                    merged: List[object] = []
                    for p in parts:
                        merged.extend(p.tolist())
                    cols[info.name] = merged
                else:
                    cols[info.name] = np.concatenate(parts)
            full = frame_from_arrays(cols, num_blocks=1)
        return self._table_of(self.build(full))

    def _execute_incremental(
        self, manifest, store, invalidated: bool,
    ) -> Dict[str, np.ndarray]:
        """Fold per-chunk partials, reading/executing only chunks whose
        partial is not cached (new, invalidated, or corrupt)."""
        from ..plan.lower import fold_partial_tables

        partials: List[Dict[str, np.ndarray]] = []
        folded = executed = 0
        live_sigs = set()
        for path, sig in manifest:
            live_sigs.add(sig)
            table = self._mem_partials.get(sig)
            if table is None and store is not None:
                table, corrupt = store.load(_partial_key(self._fp, sig))
                if corrupt:
                    m.result_recompute("corrupt_partial").inc()
                    self._recomputes["corrupt_partial"] += 1
                    _flight.record(
                        "serving.query_partial_corrupt",
                        endpoint=self.name, chunk=os.path.basename(path),
                    )
            if table is None:
                table = self._run_chunk(path)
                executed += 1
                if store is not None:
                    n = store.put(_partial_key(self._fp, sig), table)
                    m.RESULT_CACHE_BYTES.inc(n)
                    self._bytes += n
            else:
                folded += 1
            self._mem_partials[sig] = table
            partials.append(table)
        # drop partials of departed chunks from the in-memory mirror
        # (the on-disk store is content-keyed; stale entries just idle)
        for sig in list(self._mem_partials):
            if sig not in live_sigs:
                del self._mem_partials[sig]
        m.RESULT_CACHE_CHUNKS_FOLDED.inc(folded)
        self._chunks_folded += folded
        self._chunks_executed += executed
        if executed:
            reason = "invalidated" if invalidated else "cold"
            m.result_recompute(reason).inc()
            self._recomputes[reason] += 1
        return fold_partial_tables(
            partials, self._agg_keys, self._agg_ops,
            self._result_schema,
        )

    def execute(self) -> Dict[str, np.ndarray]:
        """One request's answer over the source's current contents —
        memo hit, store hit, incremental fold, or counted full
        recompute, in that order."""
        from ..plan.lower import canonical_table_order
        from ..compilecache.fingerprint import content_digest

        with self._lock:
            manifest = self._manifest()
            digest = content_digest(sig for _, sig in manifest)
            if digest == self._memo_digest:
                m.RESULT_CACHE_HITS.inc()
                self._hits += 1
                return self._memo_table
            prev = self._last_manifest
            if prev is not None:
                m.RESULT_CACHE_INVALIDATIONS.inc()
                self._invalidations += 1
                _flight.record(
                    "serving.query_invalidated", endpoint=self.name,
                    chunks=len(manifest), prev_chunks=len(prev),
                )
            # append-only ⇔ every previously-seen (path, sig) survives
            invalidated = prev is not None and not (
                {(p, s) for p, s in prev}
                <= {(p, s) for p, s in manifest}
            )
            store = self._result_store()
            if store is not None:
                table, _corrupt = store.load(
                    _result_key(self._fp, digest)
                )
                if table is not None:
                    m.RESULT_CACHE_HITS.inc()
                    self._hits += 1
                    self._memo_digest, self._memo_table = digest, table
                    self._last_manifest = manifest
                    return table
            m.RESULT_CACHE_MISSES.inc()
            self._misses += 1
            if self._inc_reason is None and self.source.frame is None:
                table = self._execute_incremental(
                    manifest, store, invalidated
                )
            else:
                reason = ("ineligible" if self._inc_reason is not None
                          else ("invalidated" if invalidated else "cold"))
                m.result_recompute(reason).inc()
                self._recomputes[reason] += 1
                table = self._execute_full(manifest)
                if self._agg_keys:
                    table = canonical_table_order(table, self._agg_keys)
            if store is not None:
                n = store.put(_result_key(self._fp, digest), table)
                m.RESULT_CACHE_BYTES.inc(n)
                self._bytes += n
            self._memo_digest, self._memo_table = digest, table
            self._last_manifest = manifest
            return table

    # -- serving surface ----------------------------------------------------

    def warm(self) -> Dict[str, object]:
        """``start()``-time warm: execute once so the first request is
        already a cache hit (and, with a persistent store armed, a
        restarted process warms WITHOUT executing — the store answers)."""
        t0 = time.perf_counter()
        before = self._hits
        table = self.execute()
        report = {
            "endpoint": self.name,
            "warm_s": round(time.perf_counter() - t0, 6),
            "from_cache": self._hits > before,
            "rows": len(next(iter(table.values()))) if table else 0,
            "fingerprint": self._fp,
        }
        logger.info("query warmup[%s]: %s", self.name, report)
        return report

    def open(self) -> None:
        with self._lock:
            self._open = True

    def close(self) -> None:
        with self._lock:
            self._open = False

    def submit(self, feeds, deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> ResultFuture:
        if feeds not in (None, {}):
            raise ValidationError(
                f"query endpoint {self.name!r} takes no feeds (its "
                "input is the registered source's current contents); "
                f"got {type(feeds).__name__}"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}) — the same "
                "contract as RetryPolicy.deadline_s"
            )
        with self._lock:
            if not self._open:
                self._rejected["closed"] += 1
                m.rejected("closed").inc()
                raise RejectedError(
                    f"query endpoint {self.name!r} is not accepting "
                    "requests (server stopped or draining)",
                    reason="closed",
                )
            self._admitted_requests += 1
            self._admitted_rows += 1
        m.REQUESTS.inc()
        m.ROWS.inc()
        fut = ResultFuture(self.name, 1)
        t0 = time.perf_counter()
        try:
            fut._set(self.execute())
        except BaseException as e:  # the dispatch-error class: the
            # future carries it (HTTP maps to 500), admission already
            # succeeded — same split as the batcher's dispatch path
            m.DISPATCH_ERRORS.inc()
            fut._fail(e)
        wall = time.perf_counter() - t0
        self._latency.observe(wall)
        m.REQUEST_LATENCY.observe(wall)
        if trace_id:
            _flight.record(
                "serving.query_request", endpoint=self.name,
                trace=trace_id, wall_s=round(wall, 6),
            )
        return fut

    def counters(self) -> Dict[str, object]:
        """Batcher-compatible snapshot for ``Server.stats()``."""
        with self._lock:
            out = {
                "queued_rows": 0,
                "admitted_requests": self._admitted_requests,
                "admitted_rows": self._admitted_rows,
                "rejected": dict(self._rejected),
                "deadline_expired": 0,
            }
        out["latency"] = self._latency.quantiles()
        return out

    def cache_stats(self) -> Dict[str, object]:
        """The result-cache rows ``Server.stats()`` publishes per
        endpoint (per-endpoint mirrors of the process-wide
        ``tftpu_result_cache_*`` series)."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "invalidations": self._invalidations,
                "bytes": self._bytes,
                "chunks_folded": self._chunks_folded,
                "chunks_executed": self._chunks_executed,
                "recomputes": dict(self._recomputes),
                "fingerprint": self._fp,
                "cacheable": self._cache_reason is None,
                "incremental": self._inc_reason is None,
            }
