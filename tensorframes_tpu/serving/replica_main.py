"""Runnable entry for the demo fleet replica:

    python -m tensorframes_tpu.serving.replica_main --demo

A separate module (never imported by the serving package) so ``-m``
does not re-execute ``replica.py``, which the package imports at init —
runpy would otherwise warn about the double module object. All logic
lives in :mod:`tensorframes_tpu.serving.replica`.
"""

from tensorframes_tpu.serving.replica import main

if __name__ == "__main__":
    raise SystemExit(main())
