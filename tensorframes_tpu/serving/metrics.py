"""Serving-layer instruments (``tftpu_serving_*``), registered at import.

The admission-control loop is only tunable if its behavior is a graph:
how deep the queue runs, why flushes fire (bucket full vs latency timer
vs drain), how much padding the bucket ladder costs, and where request
wall-clock goes (queue wait vs dispatch). Every instrument here
pre-registers at import — including every ``reason=`` label series the
batcher can emit — so an exposition always carries the full catalog
(a server that never shed load still exports ``rejected_total{...}=0``).

Label conventions follow the repo rule (TFL003): label VALUE sets are
closed and enumerated here; per-endpoint cardinality stays out of the
registry (endpoints ride flight records and trace args instead).
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..observability.latency import LATENCY_BUCKETS
from ..observability.metrics import Counter
from ..observability.metrics import counter as _counter
from ..observability.metrics import gauge as _gauge
from ..observability.metrics import histogram as _histogram

__all__ = [
    "REQUESTS", "ROWS", "REJECTED", "REJECT_REASONS", "QUEUE_DEPTH",
    "FLUSHES", "FLUSH_REASONS", "BATCH_ROWS", "PADDING_ROWS",
    "REQUEST_LATENCY", "QUEUE_WAIT", "DISPATCH_SECONDS",
    "DEADLINE_EXPIRED", "DISPATCH_ERRORS", "rejected",
    "DECODE_PHASES", "DECODE_TOKENS", "DECODE_STEPS", "DECODE_TTFT",
    "DECODE_SLOTS", "DECODE_FREE_PAGES", "DECODE_PREEMPTIONS",
    "DECODE_EVICTIONS",
    "KVSWAP_OUTS", "KVSWAP_RESUMES", "KVSWAP_FALLBACKS", "KVSWAP_BYTES",
    "PREFIX_HITS", "PREFIX_MISSES", "PREFIX_SHARED_PAGES",
    "PREFIX_EVICTIONS",
    "RESULT_CACHE_HITS", "RESULT_CACHE_MISSES",
    "RESULT_CACHE_INVALIDATIONS", "RESULT_CACHE_BYTES",
    "RESULT_CACHE_CHUNKS_FOLDED", "RESULT_CACHE_RECOMPUTES",
    "RECOMPUTE_REASONS", "result_recompute",
    "HTTP_REJECT_REASONS", "HTTP_REJECTIONS", "http_rejected",
    "IDEMPOTENT_DEDUP",
    "ROUTER_REJECT_REASONS", "ROUTER_REQUESTS", "ROUTER_REDRIVES",
    "ROUTER_REJECTED", "ROUTER_REPLICAS_LIVE", "ROUTER_REPLICA_DEAD",
    "ROUTER_REPLICA_RESTARTS", "ROUTER_DISPATCH_SECONDS",
    "ROUTER_REQUEST_LATENCY", "router_rejected",
    "REQUEST_TRACE",
]

#: Why an admission was refused (closed set — every series pre-registered).
REJECT_REASONS: Tuple[str, ...] = ("queue_full", "closed", "too_large")

#: Why a batch left the queue (closed set).
FLUSH_REASONS: Tuple[str, ...] = ("full", "timer", "drain")

#: Rows-per-flush buckets: the power-of-two ladder serving pads into.
_BATCH_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
)

REQUESTS = _counter(
    "tftpu_serving_requests_total",
    "Requests admitted into the serving queue",
)
ROWS = _counter(
    "tftpu_serving_rows_total",
    "Rows admitted into the serving queue",
)
REJECTED: Dict[str, Counter] = {
    r: _counter(
        "tftpu_serving_rejected_total",
        "Requests refused at admission, by reason (queue_full = "
        "backpressure shed, closed = server stopped/draining, "
        "too_large = request exceeds max_batch_rows)",
        labels={"reason": r},
    )
    for r in REJECT_REASONS
}
QUEUE_DEPTH = _gauge(
    "tftpu_serving_queue_depth_rows",
    "Rows currently waiting in serving queues (all endpoints)",
)
FLUSHES: Dict[str, Counter] = {
    r: _counter(
        "tftpu_serving_flushes_total",
        "Coalesced batches dispatched, by flush reason (full = bucket "
        "target reached, timer = max-latency flush, drain = shutdown)",
        labels={"reason": r},
    )
    for r in FLUSH_REASONS
}
BATCH_ROWS = _histogram(
    "tftpu_serving_batch_rows",
    "Rows per coalesced flush (pre-padding)",
    buckets=_BATCH_BUCKETS,
)
PADDING_ROWS = _counter(
    "tftpu_serving_padding_rows_total",
    "Rows added padding flushes up to the power-of-two bucket ladder",
)
REQUEST_LATENCY = _histogram(
    "tftpu_serving_request_latency_seconds",
    "Request wall-clock from submit to result ready (queue wait + "
    "dispatch) — the p50/p99 the bench serving target reports",
    buckets=LATENCY_BUCKETS,
)
QUEUE_WAIT = _histogram(
    "tftpu_serving_queue_wait_seconds",
    "Request wall-clock from submit to its flush leaving the queue",
    buckets=LATENCY_BUCKETS,
)
DISPATCH_SECONDS = _histogram(
    "tftpu_serving_dispatch_seconds",
    "Wall-clock of one coalesced flush's executor dispatch",
    buckets=LATENCY_BUCKETS,
)
DEADLINE_EXPIRED = _counter(
    "tftpu_serving_deadline_expired_total",
    "Requests failed because their deadline passed while queued",
)
REQUEST_TRACE = _counter(
    "tftpu_serving_request_trace_total",
    "Requests whose trace context crossed a process hop (Router "
    "stamped or replica adopted the X-Tftpu-Trace header) — the "
    "cross-hop tracing coverage signal (ISSUE 17)",
)
DISPATCH_ERRORS = _counter(
    "tftpu_serving_dispatch_errors_total",
    "Coalesced flushes whose dispatch raised (every member request "
    "fails with the same error)",
)


# -- iterative decode (tftpu_decode_*, ISSUE 11) ----------------------------
# The decode engine's health is a rate (tokens/sec = the tokens counter
# differentiated), a latency (TTFT — the open-loop bench gates its
# p50/p99), and three occupancy signals (running slots, free KV pages,
# and how often the pool had to preempt). Request-level latency and
# queue depth ride the shared serving instruments above — a decode
# request IS a serving request.

#: Engine phases (closed set — one executable family per phase).
DECODE_PHASES: Tuple[str, ...] = ("prefill", "decode")

DECODE_TOKENS = _counter(
    "tftpu_decode_tokens_total",
    "Newly generated tokens across all decode endpoints (replayed "
    "tokens of a preempted sequence's resume are NOT counted — they "
    "are recompute, not progress); rate = decode tokens/sec",
)
DECODE_STEPS: Dict[str, Counter] = {
    p: _counter(
        "tftpu_decode_steps_total",
        "Engine step dispatches by phase (prefill = one sequence's "
        "prompt chunk, decode = one batched token step over the "
        "running slots)",
        labels={"phase": p},
    )
    for p in DECODE_PHASES
}
DECODE_TTFT = _histogram(
    "tftpu_decode_ttft_seconds",
    "Time to first token: submit to the prompt's prefill completing "
    "(the open-loop decode bench gates p50/p99 of this)",
    buckets=LATENCY_BUCKETS,
)
DECODE_SLOTS = _gauge(
    "tftpu_decode_slot_occupancy",
    "Sequence slots currently running in the iterative decode batch",
)
DECODE_FREE_PAGES = _gauge(
    "tftpu_decode_free_pages",
    "Free pages across decode KV pools (the headroom preemption "
    "defends)",
)
DECODE_PREEMPTIONS = _counter(
    "tftpu_decode_preemptions_total",
    "Running sequences preempted because the KV pool had no free page "
    "(evicted, requeued at the head, resumed bit-identically later)",
)
DECODE_EVICTIONS = _counter(
    "tftpu_decode_evictions_total",
    "KV pages evicted by preemption (freed from a preempted "
    "sequence's table)",
)


# -- KV memory hierarchy (tftpu_kvswap_* / tftpu_prefix_cache_*, ISSUE 19) --
# Two page lifecycles beyond the free/owned pair: an evicted sequence's
# pages host-swapping through the block store (resume = restore, not
# recompute), and read-only prefix pages shared across requests by
# content address. The swap counters split the preemption story —
# preemptions_total keeps counting every eviction, kvswap_out_total the
# subset whose pages went to disk, and resume vs fallback says whether
# the swap actually paid off or corruption pushed the request back onto
# the replay path. The prefix counters are the cache's hit-rate and
# residency: hits/misses differentiated = how often a prompt's prefill
# was skipped, shared_pages = pages pinned read-only right now.

KVSWAP_OUTS = _counter(
    "tftpu_kvswap_out_total",
    "Preempted sequences whose KV pages were host-swapped to the "
    "block store (CRC-checked segment) instead of discarded",
)
KVSWAP_RESUMES = _counter(
    "tftpu_kvswap_resume_total",
    "Sequences resumed by restoring host-swapped pages bit-identically "
    "(no prefill or teacher-forced replay ran)",
)
KVSWAP_FALLBACKS = _counter(
    "tftpu_kvswap_fallback_total",
    "Swap-in attempts abandoned for the recompute-replay path (segment "
    "corruption or store failure — the request still completes; the "
    "store's quarantine counters name the root cause)",
)
KVSWAP_BYTES = _counter(
    "tftpu_kvswap_bytes_total",
    "Bytes of KV page payload written to the block store by "
    "per-sequence swap-out",
)
PREFIX_HITS = _counter(
    "tftpu_prefix_cache_hits_total",
    "Prompt admissions that reused at least one shared prefix page "
    "(those prefill chunks were skipped entirely)",
)
PREFIX_MISSES = _counter(
    "tftpu_prefix_cache_misses_total",
    "Prompt admissions that found no shared prefix page (cold prefill "
    "ran for the whole prompt; only counted when the cache is armed)",
)
PREFIX_SHARED_PAGES = _gauge(
    "tftpu_prefix_cache_shared_pages",
    "Pages currently published read-only in the content-addressed "
    "prefix cache (any refcount, including cached-but-unreferenced)",
)
PREFIX_EVICTIONS = _counter(
    "tftpu_prefix_cache_evictions_total",
    "Shared prefix pages reclaimed to the free list under allocation "
    "pressure (only refcount-0 pages are eligible, LRU-first)",
)


# -- registered-query result cache (tftpu_result_cache_*, ISSUE 20) --------
# A registered relational endpoint's health is a hit rate (repeat
# queries served from the (plan fingerprint, content digest) keyed
# store without executing), an invalidation rate (how often the input
# partition moved under it), and the incremental split: chunks whose
# cached partials folded vs full recomputes, BY REASON — "the cache
# degraded" must always name why. Per-endpoint cardinality stays out
# of the registry (TFL003); Server.stats() carries the per-endpoint
# rows.

#: Why a registered query ran a counted full recompute (closed set).
#: cold = first sight of this input partition (nothing cached yet);
#: invalidated = a previously-seen part changed or disappeared, so the
#: cached partials no longer describe the table; ineligible = the plan
#: declined caching or incremental maintenance (TFG114 names the
#: stage); corrupt_partial = a cached chunk partial failed CRC and
#: that chunk re-executed (quarantined, never served).
RECOMPUTE_REASONS: Tuple[str, ...] = (
    "cold", "invalidated", "ineligible", "corrupt_partial",
)

RESULT_CACHE_HITS = _counter(
    "tftpu_result_cache_hits_total",
    "Registered-query requests served from the result cache (memo or "
    "persistent store) — no plan execution, no chunk read",
)
RESULT_CACHE_MISSES = _counter(
    "tftpu_result_cache_misses_total",
    "Registered-query requests whose (plan fingerprint, content "
    "digest) key was absent from the result cache",
)
RESULT_CACHE_INVALIDATIONS = _counter(
    "tftpu_result_cache_invalidations_total",
    "Input-partition digest changes observed by registered queries "
    "(the previous cached result can no longer serve; appends refresh "
    "incrementally, rewrites/removals force full recompute)",
)
RESULT_CACHE_BYTES = _counter(
    "tftpu_result_cache_bytes_total",
    "Bytes of result/partial tables published into the persistent "
    "result store by registered queries",
)
RESULT_CACHE_CHUNKS_FOLDED = _counter(
    "tftpu_result_cache_chunks_folded_total",
    "Scan chunks whose CACHED aggregate partials were folded into a "
    "registered query's refresh instead of being re-read and "
    "re-executed (the incremental-maintenance payoff counter)",
)
RESULT_CACHE_RECOMPUTES: Dict[str, Counter] = {
    r: _counter(
        "tftpu_result_cache_recomputes_total",
        "Registered-query executions that could not serve from cached "
        "results/partials, by reason (cold = first sight of the input "
        "partition, invalidated = a seen part changed/disappeared, "
        "ineligible = the plan declined caching/incremental [TFG114 "
        "names the stage], corrupt_partial = a damaged cached partial "
        "was quarantined and its chunk re-executed)",
        labels={"reason": r},
    )
    for r in RECOMPUTE_REASONS
}


def result_recompute(reason: str) -> Counter:
    """The pre-registered recompute counter for ``reason``."""
    return RESULT_CACHE_RECOMPUTES[reason]


def rejected(reason: str) -> Counter:
    """The pre-registered rejection counter for ``reason``."""
    return REJECTED[reason]


# -- hardened HTTP ingress (tftpu_serving_rejections_total, ISSUE 13) -------
# Transport-level refusals happen BEFORE a request reaches admission
# control, so they cannot ride the admission counter above: an oversized
# body, a slow-read connection, or a connection past the concurrency
# bound never becomes a queued request. A separate counter (the name the
# fleet issue assigns) keeps the two shed layers distinguishable on a
# dashboard: rejected_total spikes mean the batcher is full,
# rejections_total spikes mean the transport is under attack/overload.

#: Why the HTTP layer refused a connection/body (closed set).
HTTP_REJECT_REASONS: Tuple[str, ...] = (
    "body_too_large", "read_timeout", "conn_limit",
)

HTTP_REJECTIONS: Dict[str, Counter] = {
    r: _counter(
        "tftpu_serving_rejections_total",
        "HTTP ingress refusals before admission, by reason "
        "(body_too_large = request body over the ingress byte limit "
        "[413], read_timeout = connection read stalled past the "
        "per-connection timeout [408/close], conn_limit = concurrent "
        "connection bound reached [503])",
        labels={"reason": r},
    )
    for r in HTTP_REJECT_REASONS
}

IDEMPOTENT_DEDUP = _counter(
    "tftpu_serving_idempotent_dedup_total",
    "Submissions deduplicated by idempotency key (a redriven or "
    "retried dispatch joined the original request's future instead of "
    "executing again)",
)


def http_rejected(reason: str) -> Counter:
    """The pre-registered ingress rejection counter for ``reason``."""
    return HTTP_REJECTIONS[reason]


# -- fleet router (tftpu_router_*, ISSUE 13) --------------------------------
# The router is the one place that sees the whole fleet: how many
# replicas are routable, how often a dispatch had to be redriven to a
# survivor, and what the client-visible latency is THROUGH failures.
# Per-replica cardinality stays out of the registry (TFL003) — ranks
# ride flight records (router.* family) and the router's healthz body.

#: Why the router refused an ingress request (closed set).
ROUTER_REJECT_REASONS: Tuple[str, ...] = ("no_replica", "deadline")

ROUTER_REQUESTS = _counter(
    "tftpu_router_requests_total",
    "Ingress requests admitted by the fleet router",
)
ROUTER_REDRIVES = _counter(
    "tftpu_router_redrives_total",
    "Dispatches redriven to a surviving replica after the chosen "
    "replica failed mid-request (same idempotency key, original "
    "deadline)",
)
ROUTER_REJECTED: Dict[str, Counter] = {
    r: _counter(
        "tftpu_router_rejected_total",
        "Ingress requests the router refused, by reason (no_replica = "
        "no live non-draining replica, deadline = the request's budget "
        "lapsed before any dispatch succeeded)",
        labels={"reason": r},
    )
    for r in ROUTER_REJECT_REASONS
}
ROUTER_REPLICAS_LIVE = _gauge(
    "tftpu_router_replicas_live",
    "Replicas the router currently considers routable (state=running, "
    "fresh heartbeat, healthz reachable)",
)
ROUTER_REPLICA_DEAD = _counter(
    "tftpu_router_replica_dead_total",
    "Replicas newly marked dead by the router/fleet (process exit, "
    "stale heartbeat, or repeated scrape failure)",
)
ROUTER_REPLICA_RESTARTS = _counter(
    "tftpu_router_replica_restarts_total",
    "Replica processes respawned by the serving fleet supervisor "
    "after a death",
)
ROUTER_DISPATCH_SECONDS = _histogram(
    "tftpu_router_dispatch_seconds",
    "Wall-clock of one router->replica dispatch attempt (successful "
    "or failed; redrives observe once per attempt)",
    buckets=LATENCY_BUCKETS,
)
ROUTER_REQUEST_LATENCY = _histogram(
    "tftpu_router_request_latency_seconds",
    "Ingress request wall-clock through the router (admission to "
    "relayed reply, including any redrives) — the fleet bench's p99 "
    "gate reads this",
    buckets=LATENCY_BUCKETS,
)


def router_rejected(reason: str) -> Counter:
    """The pre-registered router rejection counter for ``reason``."""
    return ROUTER_REJECTED[reason]
