"""Serving-layer instruments (``tftpu_serving_*``), registered at import.

The admission-control loop is only tunable if its behavior is a graph:
how deep the queue runs, why flushes fire (bucket full vs latency timer
vs drain), how much padding the bucket ladder costs, and where request
wall-clock goes (queue wait vs dispatch). Every instrument here
pre-registers at import — including every ``reason=`` label series the
batcher can emit — so an exposition always carries the full catalog
(a server that never shed load still exports ``rejected_total{...}=0``).

Label conventions follow the repo rule (TFL003): label VALUE sets are
closed and enumerated here; per-endpoint cardinality stays out of the
registry (endpoints ride flight records and trace args instead).
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..observability.latency import LATENCY_BUCKETS
from ..observability.metrics import Counter
from ..observability.metrics import counter as _counter
from ..observability.metrics import gauge as _gauge
from ..observability.metrics import histogram as _histogram

__all__ = [
    "REQUESTS", "ROWS", "REJECTED", "REJECT_REASONS", "QUEUE_DEPTH",
    "FLUSHES", "FLUSH_REASONS", "BATCH_ROWS", "PADDING_ROWS",
    "REQUEST_LATENCY", "QUEUE_WAIT", "DISPATCH_SECONDS",
    "DEADLINE_EXPIRED", "DISPATCH_ERRORS", "rejected",
    "DECODE_PHASES", "DECODE_TOKENS", "DECODE_STEPS", "DECODE_TTFT",
    "DECODE_SLOTS", "DECODE_FREE_PAGES", "DECODE_PREEMPTIONS",
    "DECODE_EVICTIONS",
]

#: Why an admission was refused (closed set — every series pre-registered).
REJECT_REASONS: Tuple[str, ...] = ("queue_full", "closed", "too_large")

#: Why a batch left the queue (closed set).
FLUSH_REASONS: Tuple[str, ...] = ("full", "timer", "drain")

#: Rows-per-flush buckets: the power-of-two ladder serving pads into.
_BATCH_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
)

REQUESTS = _counter(
    "tftpu_serving_requests_total",
    "Requests admitted into the serving queue",
)
ROWS = _counter(
    "tftpu_serving_rows_total",
    "Rows admitted into the serving queue",
)
REJECTED: Dict[str, Counter] = {
    r: _counter(
        "tftpu_serving_rejected_total",
        "Requests refused at admission, by reason (queue_full = "
        "backpressure shed, closed = server stopped/draining, "
        "too_large = request exceeds max_batch_rows)",
        labels={"reason": r},
    )
    for r in REJECT_REASONS
}
QUEUE_DEPTH = _gauge(
    "tftpu_serving_queue_depth_rows",
    "Rows currently waiting in serving queues (all endpoints)",
)
FLUSHES: Dict[str, Counter] = {
    r: _counter(
        "tftpu_serving_flushes_total",
        "Coalesced batches dispatched, by flush reason (full = bucket "
        "target reached, timer = max-latency flush, drain = shutdown)",
        labels={"reason": r},
    )
    for r in FLUSH_REASONS
}
BATCH_ROWS = _histogram(
    "tftpu_serving_batch_rows",
    "Rows per coalesced flush (pre-padding)",
    buckets=_BATCH_BUCKETS,
)
PADDING_ROWS = _counter(
    "tftpu_serving_padding_rows_total",
    "Rows added padding flushes up to the power-of-two bucket ladder",
)
REQUEST_LATENCY = _histogram(
    "tftpu_serving_request_latency_seconds",
    "Request wall-clock from submit to result ready (queue wait + "
    "dispatch) — the p50/p99 the bench serving target reports",
    buckets=LATENCY_BUCKETS,
)
QUEUE_WAIT = _histogram(
    "tftpu_serving_queue_wait_seconds",
    "Request wall-clock from submit to its flush leaving the queue",
    buckets=LATENCY_BUCKETS,
)
DISPATCH_SECONDS = _histogram(
    "tftpu_serving_dispatch_seconds",
    "Wall-clock of one coalesced flush's executor dispatch",
    buckets=LATENCY_BUCKETS,
)
DEADLINE_EXPIRED = _counter(
    "tftpu_serving_deadline_expired_total",
    "Requests failed because their deadline passed while queued",
)
DISPATCH_ERRORS = _counter(
    "tftpu_serving_dispatch_errors_total",
    "Coalesced flushes whose dispatch raised (every member request "
    "fails with the same error)",
)


# -- iterative decode (tftpu_decode_*, ISSUE 11) ----------------------------
# The decode engine's health is a rate (tokens/sec = the tokens counter
# differentiated), a latency (TTFT — the open-loop bench gates its
# p50/p99), and three occupancy signals (running slots, free KV pages,
# and how often the pool had to preempt). Request-level latency and
# queue depth ride the shared serving instruments above — a decode
# request IS a serving request.

#: Engine phases (closed set — one executable family per phase).
DECODE_PHASES: Tuple[str, ...] = ("prefill", "decode")

DECODE_TOKENS = _counter(
    "tftpu_decode_tokens_total",
    "Newly generated tokens across all decode endpoints (replayed "
    "tokens of a preempted sequence's resume are NOT counted — they "
    "are recompute, not progress); rate = decode tokens/sec",
)
DECODE_STEPS: Dict[str, Counter] = {
    p: _counter(
        "tftpu_decode_steps_total",
        "Engine step dispatches by phase (prefill = one sequence's "
        "prompt chunk, decode = one batched token step over the "
        "running slots)",
        labels={"phase": p},
    )
    for p in DECODE_PHASES
}
DECODE_TTFT = _histogram(
    "tftpu_decode_ttft_seconds",
    "Time to first token: submit to the prompt's prefill completing "
    "(the open-loop decode bench gates p50/p99 of this)",
    buckets=LATENCY_BUCKETS,
)
DECODE_SLOTS = _gauge(
    "tftpu_decode_slot_occupancy",
    "Sequence slots currently running in the iterative decode batch",
)
DECODE_FREE_PAGES = _gauge(
    "tftpu_decode_free_pages",
    "Free pages across decode KV pools (the headroom preemption "
    "defends)",
)
DECODE_PREEMPTIONS = _counter(
    "tftpu_decode_preemptions_total",
    "Running sequences preempted because the KV pool had no free page "
    "(evicted, requeued at the head, resumed bit-identically later)",
)
DECODE_EVICTIONS = _counter(
    "tftpu_decode_evictions_total",
    "KV pages evicted by preemption (freed from a preempted "
    "sequence's table)",
)


def rejected(reason: str) -> Counter:
    """The pre-registered rejection counter for ``reason``."""
    return REJECTED[reason]
