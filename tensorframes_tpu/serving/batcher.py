"""Continuous batcher: coalesce small requests into bucket-ladder flushes.

One batcher per registered endpoint. Requests (a few rows each) queue
under a condition variable; a worker thread flushes a coalesced batch
when the pending rows reach the bucket target (``reason=full``), when
the oldest request has waited ``max_latency_s`` (``reason=timer``), or
at shutdown (``reason=drain``). Every flush pads its lead dim through
the SAME power-of-two ladder the executor and ``compilecache.warmup``
use (:func:`~tensorframes_tpu.ops.executor.bucket_rows`), so a warmed
server dispatches with **zero steady-state compiles** — each flush is
an AOT-cache hit regardless of the request-size mix.

Correctness contract: the program is row-independent (vmapped, the
map_rows semantics), so row *i* of a coalesced flush is **bit-identical**
to the same row dispatched solo — coalescing is purely a throughput
transform. Padding rows replicate the last real row (the executor's
``pad_lead_dim``) and are sliced off before scatter, so they can never
leak into a result.

Lifecycle and failure shape:

* admission is **bounded**: past ``max_queue_rows`` the offer raises
  :class:`RejectedError` immediately — overload sheds with a counted
  rejection (``tftpu_serving_rejected_total{reason=queue_full}``)
  instead of a hang, the same boundedness bargain as the fleet
  watchdogs (docs/resilience.md).
* per-request **deadlines** follow ``RetryPolicy.deadline_s`` semantics
  (resilience/retry.py): a total-elapsed wall-clock cap from submit,
  covering queue wait and dispatch scheduling. A request whose budget
  expires while queued fails with :class:`DeadlineExceededError`; a
  dedicated expirer thread wakes at the earliest pending deadline —
  expiry latency is bounded by the clock, not by traffic, even while
  the worker is blocked inside a slow dispatch.
* **drain** flushes every queued request before the worker exits —
  graceful shutdown completes admitted work, it never abandons futures.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..observability import context as _context
from ..observability import events as _events
from ..observability import flight as _flight
from ..observability.latency import LATENCY_BUCKETS
from ..observability.metrics import Histogram
from ..ops.executor import bucket_rows
from ..resilience.faults import delay_point, fault_point, register_site
from ..utils import get_logger
from . import metrics as m

logger = get_logger(__name__)

register_site(
    "serving.flush",
    "continuous-batcher flush body, before the coalesced dispatch — an "
    "injected error fails every request in the batch (counted, "
    "futures resolve); an injected Delay stalls the flush so queued "
    "deadlines expire (the deadline-drill shape)",
)


class ServingError(RuntimeError):
    """Base class of serving-layer failures."""


class RejectedError(ServingError):
    """Admission refused (backpressure / closed / oversized request).
    ``reason`` is one of :data:`metrics.REJECT_REASONS`."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline passed before its flush dispatched."""


class ResultFuture:
    """Handle to one request's eventual per-row results.

    ``result(timeout)`` blocks for the scattered output columns (a dict
    name → array holding exactly this request's rows) or raises the
    request's failure (:class:`DeadlineExceededError`, the dispatch
    error, or :class:`ServingError` on abandon)."""

    __slots__ = ("_done", "_value", "_exc", "rows", "endpoint")

    def __init__(self, endpoint: str, rows: int):
        self._done = threading.Event()
        self._value: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None
        self.rows = rows
        self.endpoint = endpoint

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"serving result not ready after {timeout}s "
                f"(endpoint {self.endpoint!r})"
            )
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"serving result not ready after {timeout}s "
                f"(endpoint {self.endpoint!r})"
            )
        return self._exc

    def _set(self, value: Dict[str, np.ndarray]) -> None:
        self._value = value
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()


class _Request:
    __slots__ = ("feeds", "rows", "t_submit", "deadline", "future",
                 "trace_id")

    def __init__(self, feeds, rows, deadline_s: Optional[float],
                 future: ResultFuture,
                 trace_id: Optional[str] = None):
        self.feeds = feeds
        self.rows = rows
        self.t_submit = time.perf_counter()
        self.deadline = (
            None if deadline_s is None else self.t_submit + deadline_s
        )
        self.future = future
        #: cross-hop request id (ISSUE 17): set from the router's trace
        #: header (or the submit thread's bound request context) so the
        #: flush/request spans this request rides carry the SAME id the
        #: router's ingress span does — the flush serves many requests,
        #: so the id lives on the request slot, not a thread-local
        self.trace_id = trace_id


class ContinuousBatcher:
    """The per-endpoint queue + worker. ``dispatch(feeds, rows)`` is the
    endpoint's coalesced entry (executor ``run_rows_bucketed`` under the
    server's retry policy); results scatter back by request offset.

    **Pull mode** (``dispatch=None``): no worker thread — an external
    consumer (the iterative decode engine) drains the queue itself with
    :meth:`poll` and can push preempted work back with
    :meth:`requeue_front`. In pull mode the queue IS the consumer's
    slot-wait queue, and the dedicated expirer thread covers it exactly
    as it covers push-mode flushes: a request waiting for a free decode
    slot (or re-waiting after preemption) whose deadline lapses fails
    with :class:`DeadlineExceededError` on the clock — a full KV pool
    can never hold a request past its deadline (ISSUE 11 satellite)."""

    def __init__(
        self,
        name: str,
        dispatch: Optional[
            Callable[[Dict[str, np.ndarray], int], Dict[str, np.ndarray]]
        ],
        max_batch_rows: int,
        max_latency_s: float,
        max_queue_rows: int,
    ):
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        if max_latency_s < 0:
            raise ValueError("max_latency_s must be >= 0")
        if max_queue_rows < max_batch_rows:
            raise ValueError(
                "max_queue_rows must be >= max_batch_rows (a queue that "
                "cannot hold one full batch deadlocks admission)"
            )
        self.name = name
        self._dispatch = dispatch
        self.max_batch_rows = int(max_batch_rows)
        self.max_latency_s = float(max_latency_s)
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._queued_rows = 0
        # this batcher's own admission counters (under _cond): the
        # registry series are process-wide, but Server.stats()/healthz
        # must report THIS server's traffic — a fresh server in the same
        # process starts from zero, not from a predecessor's totals
        self._admitted_requests = 0
        self._admitted_rows = 0
        self._rejected = {r: 0 for r in m.REJECT_REASONS}
        self._deadline_expired = 0
        # per-endpoint latency histogram, IN-OBJECT (TFL003 keeps
        # endpoint names out of the registry's label space): feeds the
        # p50/p95/p99 Server.stats()/healthz report per endpoint
        self._latency = Histogram(
            "serving_endpoint_latency_seconds",
            f"request latency for endpoint {name!r} (submit → result)",
            (), threading.Lock(), buckets=LATENCY_BUCKETS,
        )
        self._open = False
        self._draining = False
        self._worker: Optional[threading.Thread] = None
        self._expirer: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    @property
    def pull_mode(self) -> bool:
        return self._dispatch is None

    def start(self) -> None:
        with self._cond:
            if self._open:
                return
            self._open = True
            self._draining = False
            if not self.pull_mode:
                self._worker = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"tfs-serving-{self.name}",
                )
                self._worker.start()
            # deadlines are enforced by their own thread: the worker can
            # be blocked inside a multi-second dispatch (or, in pull
            # mode, the consumer inside a multi-second decode step), and
            # a queued request's expiry must be bounded by the clock,
            # not by the flush in flight
            self._expirer = threading.Thread(
                target=self._expire_run, daemon=True,
                name=f"tfs-serving-{self.name}-deadlines",
            )
            self._expirer.start()

    def close(self, drain: bool = True) -> None:
        """Close admission WITHOUT joining the threads: with ``drain``
        the queued requests stay for the worker/consumer to finish,
        else they fail with :class:`ServingError` now. Pull-mode
        consumers call this first, drain via :meth:`poll`, then
        :meth:`stop` to join the expirer."""
        with self._cond:
            if not self._open and not self._queue:
                self._cond.notify_all()
                return
            self._open = False
            if drain:
                self._draining = True
            else:
                while self._queue:
                    req = self._queue.popleft()
                    self._queued_rows -= req.rows
                    m.QUEUE_DEPTH.dec(req.rows)
                    req.future._fail(ServingError(
                        f"server stopped without drain; request to "
                        f"{self.name!r} abandoned"
                    ))
            self._cond.notify_all()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Close admission; with ``drain`` flush everything queued before
        the worker exits, else fail queued requests with
        :class:`ServingError`. Joins the worker (bounded by ``timeout``).
        In pull mode the consumer must have drained (or be draining) the
        queue — the expirer exits once the queue is empty and closed."""
        with self._cond:
            if not self._open and self._worker is None \
                    and self._expirer is None:
                return
        self.close(drain=drain)
        with self._cond:
            worker = self._worker
            expirer = self._expirer
        if worker is not None:
            worker.join(timeout)
            if worker.is_alive():
                logger.warning(
                    "serving batcher %r worker still draining after "
                    "stop timeout", self.name,
                )
        if expirer is not None:
            expirer.join(timeout)
        with self._cond:
            if self._worker is worker:
                self._worker = None
            if self._expirer is expirer:
                self._expirer = None

    @property
    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def counters(self) -> Dict[str, object]:
        """One consistent snapshot of this batcher's queue depth and
        admission counters (the registry keeps the process-wide series)."""
        with self._cond:
            out = {
                "queued_rows": self._queued_rows,
                "admitted_requests": self._admitted_requests,
                "admitted_rows": self._admitted_rows,
                "rejected": dict(self._rejected),
                "deadline_expired": self._deadline_expired,
            }
        # quantiles outside _cond: the histogram has its own lock
        out["latency"] = self._latency.quantiles()
        return out

    # -- admission ----------------------------------------------------------

    def offer(self, feeds: Dict[str, np.ndarray], rows: int,
              deadline_s: Optional[float],
              trace_id: Optional[str] = None) -> ResultFuture:
        if rows > self.max_batch_rows:
            m.rejected("too_large").inc()
            with self._cond:
                self._rejected["too_large"] += 1
            raise RejectedError(
                f"request of {rows} rows exceeds max_batch_rows="
                f"{self.max_batch_rows} for endpoint {self.name!r} — "
                "split the request or raise ServingConfig.max_batch_rows",
                reason="too_large",
            )
        future = ResultFuture(self.name, rows)
        req = _Request(feeds, rows, deadline_s, future,
                       trace_id or _context.current_request())
        with self._cond:
            if not self._open:
                m.rejected("closed").inc()
                self._rejected["closed"] += 1
                raise RejectedError(
                    f"endpoint {self.name!r} is not accepting requests "
                    "(server stopped or draining)",
                    reason="closed",
                )
            if self._queued_rows + rows > self.max_queue_rows:
                m.rejected("queue_full").inc()
                self._rejected["queue_full"] += 1
                _flight.record(
                    "serving.reject", endpoint=self.name,
                    reason="queue_full", rows=rows,
                    queued_rows=self._queued_rows,
                )
                raise RejectedError(
                    f"serving queue for {self.name!r} is full "
                    f"({self._queued_rows} rows queued, bound "
                    f"{self.max_queue_rows}) — overload sheds instead "
                    "of hanging; retry with backoff or scale out",
                    reason="queue_full",
                )
            self._queue.append(req)
            self._queued_rows += rows
            self._admitted_requests += 1
            self._admitted_rows += rows
            m.QUEUE_DEPTH.inc(rows)
            self._cond.notify_all()
        m.REQUESTS.inc()
        m.ROWS.inc(rows)
        return future

    # -- pull-mode consumer API (the decode engine's slot-wait queue) -------

    def poll(self, max_requests: int,
             can_take: Optional[Callable[["_Request"], bool]] = None
             ) -> List["_Request"]:
        """Take up to ``max_requests`` FIFO requests (expired ones are
        failed first, never returned). ``can_take`` gates the HEAD
        request — the decode engine passes its has-pages-for-this-prompt
        predicate, so admission stays FIFO (no starvation by smaller
        later prompts). Returns ``[]`` when nothing is takeable."""
        out: List[_Request] = []
        with self._cond:
            self._expire_locked(time.perf_counter())
            while self._queue and len(out) < max_requests:
                if can_take is not None and not can_take(self._queue[0]):
                    break
                req = self._queue.popleft()
                self._queued_rows -= req.rows
                m.QUEUE_DEPTH.dec(req.rows)
                out.append(req)
            if out:
                # the expirer (and a draining stop()) recompute their
                # wait the moment the queue shrinks
                self._cond.notify_all()
        return out

    def requeue_front(self, req: "_Request") -> bool:
        """Put an already-admitted request back at the HEAD of the queue
        (preemption: the engine evicted its pages and it must re-wait
        for a slot — oldest first, so it rejoins before newer arrivals).
        Deliberately exempt from the ``max_queue_rows`` bound: the
        request was admitted once; re-shedding it would turn preemption
        into silent loss. Its original deadline keeps running (total
        elapsed from submit — a full pool cannot hold it past that).
        Returns False (failing the future) only when the batcher was
        stopped without drain."""
        with self._cond:
            if not self._open and not self._draining:
                req.future._fail(ServingError(
                    f"server stopped without drain; preempted request "
                    f"to {self.name!r} abandoned"
                ))
                return False
            self._queue.appendleft(req)
            self._queued_rows += req.rows
            m.QUEUE_DEPTH.inc(req.rows)
            self._cond.notify_all()
        return True

    def wait_for_work(self, timeout: Optional[float]) -> bool:
        """Block until the queue is non-empty, admission closes, or
        ``timeout`` elapses; True iff work is queued. The pull
        consumer's idle wait (instead of a busy poll loop)."""
        with self._cond:
            if not self._queue and self._open:
                self._cond.wait(timeout)
            return bool(self._queue)

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    # -- worker -------------------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        """Fail queued requests whose deadline passed (caller holds the
        lock). FIFO order is preserved for the survivors."""
        if not any(r.deadline is not None and r.deadline <= now
                   for r in self._queue):
            return
        kept: collections.deque = collections.deque()
        for req in self._queue:
            if req.deadline is not None and req.deadline <= now:
                self._queued_rows -= req.rows
                m.QUEUE_DEPTH.dec(req.rows)
                m.DEADLINE_EXPIRED.inc()
                self._deadline_expired += 1
                _flight.record(
                    "serving.deadline", endpoint=self.name,
                    rows=req.rows,
                    waited_s=round(now - req.t_submit, 6),
                )
                req.future._fail(DeadlineExceededError(
                    f"request to {self.name!r} expired after "
                    f"{now - req.t_submit:.4f}s in queue (deadline_s "
                    "semantics: total elapsed wall-clock, like "
                    "RetryPolicy.deadline_s)"
                ))
            else:
                kept.append(req)
        self._queue = kept

    def _wait_timeout_locked(self, now: float) -> Optional[float]:
        """Seconds until the next actionable instant (oldest request's
        flush timer or the earliest deadline); None = wait for work."""
        wake = None
        if self._queue:
            wake = self._queue[0].t_submit + self.max_latency_s
        for req in self._queue:
            if req.deadline is not None:
                wake = req.deadline if wake is None else min(
                    wake, req.deadline
                )
        return None if wake is None else max(0.0, wake - now)

    def _run(self) -> None:
        while True:
            with self._cond:
                while True:
                    now = time.perf_counter()
                    self._expire_locked(now)
                    if self._queue and self._queued_rows >= self.max_batch_rows:
                        batch, reason = self._pop_locked(), "full"
                        break
                    if self._queue and (
                        now - self._queue[0].t_submit >= self.max_latency_s
                    ):
                        batch, reason = self._pop_locked(), "timer"
                        break
                    if self._draining:
                        if self._queue:
                            batch, reason = self._pop_locked(), "drain"
                            break
                        self._cond.notify_all()  # release the expirer
                        return  # drained and closed: worker exits
                    if not self._open:
                        self._cond.notify_all()  # release the expirer
                        return
                    self._cond.wait(self._wait_timeout_locked(now))
            self._flush(batch, reason)

    def _expire_run(self) -> None:
        """The deadline thread: expire queued requests the moment their
        budget lapses, independently of the worker (which may be blocked
        inside a dispatch — ``_flush`` runs OUTSIDE the lock, so expiry
        stays clock-bounded even mid-flush). Exits once the batcher is
        closed and its queue is empty."""
        while True:
            with self._cond:
                if not self._open and not self._queue:
                    return
                now = time.perf_counter()
                self._expire_locked(now)
                if not self._open and not self._queue:
                    return
                wake = None
                for req in self._queue:
                    if req.deadline is not None:
                        wake = req.deadline if wake is None else min(
                            wake, req.deadline
                        )
                self._cond.wait(
                    None if wake is None else max(0.0, wake - now)
                )

    def _pop_locked(self) -> List[_Request]:
        """Pop a FIFO prefix of requests totalling <= max_batch_rows
        (always at least one — admission bounds any single request)."""
        batch: List[_Request] = []
        rows = 0
        while self._queue and rows + self._queue[0].rows <= self.max_batch_rows:
            req = self._queue.popleft()
            rows += req.rows
            batch.append(req)
        self._queued_rows -= rows
        m.QUEUE_DEPTH.dec(rows)
        return batch

    def _flush(self, batch: List[_Request], reason: str) -> None:
        t0 = time.perf_counter()
        n = sum(r.rows for r in batch)
        m.FLUSHES[reason].inc()
        m.BATCH_ROWS.observe(n)
        m.PADDING_ROWS.inc(bucket_rows(n) - n)
        for req in batch:
            m.QUEUE_WAIT.observe(t0 - req.t_submit)
        try:
            delay_point("serving.flush")
            fault_point("serving.flush")
            feeds = {
                k: np.concatenate([np.asarray(r.feeds[k]) for r in batch])
                for k in batch[0].feeds
            } if len(batch) > 1 else dict(batch[0].feeds)
            outs = self._dispatch(feeds, n)
        except BaseException as e:
            m.DISPATCH_ERRORS.inc()
            _flight.record(
                "serving.error", endpoint=self.name, reason=reason,
                rows=n, requests=len(batch),
                error=type(e).__name__, message=str(e),
            )
            for req in batch:
                req.future._fail(e)
            return
        dt = time.perf_counter() - t0
        m.DISPATCH_SECONDS.observe(dt)
        _flight.record(
            "serving.flush", endpoint=self.name, reason=reason,
            rows=n, requests=len(batch), seconds=round(dt, 6),
        )
        if _events.TRACER.enabled:
            args = {"endpoint": self.name, "reason": reason,
                    "rows": n, "requests": len(batch)}
            rids = [r.trace_id for r in batch if r.trace_id]
            if rids:
                args["request_ids"] = rids[:16]
            _events.TRACER.emit_complete(
                "serving.flush", t0, dt, args=args, cat="serving",
            )
        off = 0
        done_t = time.perf_counter()
        for req in batch:
            # copy: a request's result must not pin the whole flush
            # buffer (nor alias its neighbors') for the future's lifetime
            req.future._set({
                k: np.array(v[off:off + req.rows]) for k, v in outs.items()
            })
            off += req.rows
            latency = done_t - req.t_submit
            m.REQUEST_LATENCY.observe(latency)
            self._latency.observe(latency)
            if _events.TRACER.enabled:
                args = {"endpoint": self.name, "rows": req.rows}
                if req.trace_id:
                    args["request_id"] = req.trace_id
                _events.TRACER.emit_complete(
                    "serving.request", req.t_submit, latency, args=args,
                    cat="serving",
                )
