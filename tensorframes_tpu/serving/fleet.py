"""Scale-out serving: a supervised multi-replica fleet behind one
fault-tolerant ingress (ISSUE 13 tentpole, ROADMAP #2).

Everything here is composition, not invention — the parts all exist:

* **supervision** (PR 8): replicas heartbeat into a shared rendezvous
  dir with the same :class:`~tensorframes_tpu.resilience.fleet`
  machinery ``supervise()`` uses; the fleet reaps crashed processes and
  declares wedged ones dead from stale beats. The recovery unit differs
  deliberately: a training fleet is a single SPMD program, so PR 8
  restarts the **whole fleet**; serving replicas are **independent**
  servers, so a death restarts exactly ONE replica while the survivors
  keep taking traffic — that is what keeps p99 bounded through a
  ``kill -9``.
* **the warm store** (PR 5/10): every replica shares one
  ``TFTPU_COMPILE_CACHE``. The first replica's warmup publishes each
  bucket-ladder executable once; every later — and every RESTARTED —
  replica's warmup is pure store hits: **zero XLA compiles**, asserted
  over the restarted replica's healthz process counters
  (``xla_compiles == 0``, ``compile_cache_hits > 0``) and hard-gated in
  ``python bench.py serving-fleet``.
* **the server** (PR 9/11): each replica keeps the whole single-process
  fast path — continuous batcher, bucket ladder, deadlines, decode —
  untouched. The fleet layer never forks the API (the DrJAX rule,
  arxiv 2403.07128): a replica is just ``serve_replica(Server(...))``.
* **the router** (this PR): one ingress that load-balances by scraped
  queue depth, never routes to a dead/draining/starting replica, and
  redrives failed dispatches to survivors under the original deadline
  with idempotency-key dedup.

Lifecycle: ``start()`` spawns N replica processes (rank env identical
to ``supervise()``'s: run id, process index, fleet dir, attempt,
flight spool — plus the shared compile store), waits for readiness,
and opens the ingress. The supervision thread watches process exits
and heartbeats; a death marks the replica dead at the router
(in-flight requests to it redrive immediately), then respawns that
rank — crash restarts draw from ``max_restarts``; clean exits (a
drained replica — the rolling-restart flow) respawn without consuming
budget. ``stop()`` drains every replica over HTTP (state ``draining``
→ ``stopped``), escalates SIGTERM → SIGKILL for stragglers, and shuts
the router down.
"""

from __future__ import annotations

import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Union

from ..config import get_config
from ..observability import context as _context
from ..observability import flight as _flight
from ..utils import get_logger
from ..resilience import fleet as _fleet
from . import metrics as m
from .router import Router, RouterConfig, http_json
from .replica import card_addr, read_cards

logger = get_logger(__name__)

__all__ = ["ServingFleet", "FleetDegradedError"]

Cmd = Union[Sequence[str], Callable[[int], Sequence[str]]]


class FleetDegradedError(RuntimeError):
    """The restart budget ran out with replicas still down."""


class ServingFleet:
    """N supervised replica server processes + one router ingress.

    ``cmd`` is the replica argv (or ``cmd(rank) -> argv``) — a process
    that calls :func:`~tensorframes_tpu.serving.replica.serve_replica`
    (e.g. ``python -m tensorframes_tpu.serving.replica_main --demo``).
    The
    fleet owns the environment contract: each rank gets the PR 8 fleet
    identity (``TFTPU_RUN_ID``/``TFTPU_PROCESS_INDEX``/
    ``TFTPU_FLEET_DIR``/``TFTPU_FLEET_ATTEMPT``/``TFTPU_FLIGHT_DIR``)
    plus ``TFTPU_COMPILE_CACHE`` pointing at ONE shared store, so a
    restarted replica warms with zero XLA compiles.

    Context-manager friendly::

        with ServingFleet(cmd, 3) as fleet:
            requests.post(fleet.url + "/v1/score", json={...})
    """

    def __init__(
        self,
        cmd: Cmd,
        num_replicas: int,
        *,
        rendezvous_dir: Optional[str] = None,
        compile_cache: Optional[str] = None,
        max_restarts: int = 4,
        heartbeat_timeout_s: Optional[float] = None,
        poll_s: float = 0.05,
        ready_timeout_s: float = 120.0,
        grace_s: float = 5.0,
        env: Optional[Dict[str, str]] = None,
        inherit_env: bool = True,
        run_id: Optional[str] = None,
        flight_dir: Optional[str] = None,
        router_config: Optional[RouterConfig] = None,
        ingress_port: int = 0,
        ingress_addr: str = "127.0.0.1",
    ):
        if num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {num_replicas}"
            )
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}"
            )
        self.cmd = cmd
        self.num_replicas = int(num_replicas)
        self.rendezvous_dir = rendezvous_dir or tempfile.mkdtemp(
            prefix="tftpu-serving-fleet-"
        )
        self.compile_cache = compile_cache or os.path.join(
            self.rendezvous_dir, "store"
        )
        self.max_restarts = int(max_restarts)
        self.heartbeat_timeout_s = (
            get_config().heartbeat_timeout_s
            if heartbeat_timeout_s is None else float(heartbeat_timeout_s)
        )
        self.poll_s = float(poll_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.grace_s = float(grace_s)
        self._env = env
        self._inherit_env = inherit_env
        self.run_id = run_id or _context.run_id()
        self._flight_explicit = flight_dir is not None
        self.flight_dir = flight_dir or os.path.join(
            self.rendezvous_dir, "flight"
        )
        self.router = Router(
            fleet_dir=self.rendezvous_dir, run_id=self.run_id,
            config=router_config or RouterConfig(
                heartbeat_timeout_s=self.heartbeat_timeout_s,
            ),
        )
        self._ingress_port = int(ingress_port)
        self._ingress_addr = ingress_addr
        self._ingress = None
        self._procs: Dict[int, subprocess.Popen] = {}
        self._attempts: Dict[int, int] = {}
        #: rank -> monotonic time of the next spawn retry (set when a
        #: respawn failed transiently; the budget was already charged)
        self._respawn_pending: Dict[int, float] = {}
        self._lock = threading.Lock()
        self._stopping = False
        self._watcher: Optional[threading.Thread] = None
        self.restarts = 0
        #: per-rank report of the latest restart's warm state, scraped
        #: from the restarted replica's healthz once it turned running:
        #: {"xla_compiles": n, "compile_cache_hits": n, ...}
        self.restart_reports: Dict[int, dict] = {}
        self.degraded = False

    # -- lifecycle ----------------------------------------------------------

    @property
    def url(self) -> str:
        if self._ingress is None:
            raise RuntimeError("fleet is not started")
        return (
            f"http://{self._ingress_addr}:{self._ingress.server_address[1]}"
        )

    def pid(self, rank: int) -> Optional[int]:
        """The replica's current pid (chaos drills ``kill -9`` it)."""
        with self._lock:
            p = self._procs.get(rank)
            return None if p is None else p.pid

    def start(self, wait_ready: bool = True) -> "ServingFleet":
        os.makedirs(self.rendezvous_dir, exist_ok=True)
        os.makedirs(self.compile_cache, exist_ok=True)
        _fleet.clear_fleet(self.rendezvous_dir, self.run_id)
        for rank in range(self.num_replicas):
            self._spawn(rank)
        self.router.start()
        self._ingress = self.router.serve(
            port=self._ingress_port, addr=self._ingress_addr
        )
        _flight.record(
            "router.fleet_start", replicas=self.num_replicas,
            rendezvous_dir=self.rendezvous_dir,
            compile_cache=self.compile_cache,
        )
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="tfs-serving-fleet"
        )
        self._watcher.start()
        if wait_ready:
            try:
                self.wait_ready()
            except BaseException:
                # readiness failed: the replicas are REAL OS children —
                # raising out of start() (and past __enter__, so
                # __exit__ never runs) must not orphan them serving
                # unsupervised
                self.stop(drain=False)
                raise
        return self

    def wait_ready(self, timeout: Optional[float] = None,
                   count: Optional[int] = None) -> None:
        """Block until ``count`` (default: all) replicas are routable.
        Raises :class:`FleetDegradedError` when the restart budget has
        run out with too few replicas live (waiting longer cannot
        help — nothing will respawn the missing ranks), and
        ``TimeoutError`` when the bound lapses first."""
        timeout = self.ready_timeout_s if timeout is None else timeout
        want = self.num_replicas if count is None else int(count)
        deadline = time.monotonic() + timeout
        while self.router.live_count() < want:
            if self.degraded:
                raise FleetDegradedError(
                    f"restart budget ({self.max_restarts}) exhausted "
                    f"with {self.router.live_count()}/{want} replicas "
                    f"live; status: {self.router.replicas()}"
                )
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self.router.live_count()}/{want} replicas "
                    f"ready after {timeout:g}s; status: "
                    f"{self.router.replicas()}"
                )
            time.sleep(0.05)

    def _spawn(self, rank: int) -> None:
        attempt = self._attempts.get(rank, -1) + 1
        self._attempts[rank] = attempt
        e = dict(os.environ) if self._inherit_env else {}
        if self._env:
            e.update(self._env)
        e.update(_context.child_env(rank))
        e["TFTPU_RUN_ID"] = self.run_id
        e["TFTPU_FLEET_DIR"] = self.rendezvous_dir
        e["TFTPU_NUM_PROCESSES"] = str(self.num_replicas)
        e["TFTPU_FLEET_ATTEMPT"] = str(attempt)
        e["TFTPU_COMPILE_CACHE"] = self.compile_cache
        if self._flight_explicit:
            e["TFTPU_FLIGHT_DIR"] = self.flight_dir
        else:
            e.setdefault("TFTPU_FLIGHT_DIR", self.flight_dir)
        argv = (
            list(self.cmd(rank)) if callable(self.cmd)
            else list(self.cmd)
        )
        proc = subprocess.Popen(argv, env=e)
        with self._lock:
            self._procs[rank] = proc
        logger.info(
            "serving fleet: replica %d spawned (pid %d, attempt %d)",
            rank, proc.pid, attempt,
        )

    # -- supervision --------------------------------------------------------

    def _watch(self) -> None:
        budget_exhausted_logged = False
        pending_ready: Dict[int, float] = {}  # rank -> restart t0
        while not self._stopping:
            time.sleep(self.poll_s)
            if self._stopping:
                return
            try:
                budget_exhausted_logged = self._watch_once(
                    pending_ready, budget_exhausted_logged
                )
            except Exception as e:
                # one transient failure (a respawn hitting ENOMEM, a
                # user cmd(rank) raising, fs wobble) must not silently
                # END supervision forever — log and keep watching
                logger.error(
                    "serving fleet: supervision scan failed "
                    "(continuing): %s", e,
                )

    def _watch_once(self, pending_ready: Dict[int, float],
                    budget_exhausted_logged: bool) -> bool:
        """One supervision scan: reap exits, judge heartbeats, record
        restarted replicas' warm reports. Returns the updated
        budget-exhausted-logged flag."""
        # 0) spawn retries from a transiently-failed respawn (the
        # budget for that death is already charged — never again here)
        now_mono = time.monotonic()
        for rank, due in list(self._respawn_pending.items()):
            if now_mono < due:
                continue
            try:
                self._spawn(rank)
                del self._respawn_pending[rank]
            except Exception as e:
                self._respawn_pending[rank] = time.monotonic() + 2.0
                logger.error(
                    "serving fleet: respawn retry of replica %d failed "
                    "(%s) — backing off", rank, e,
                )
        with self._lock:
            procs = dict(self._procs)
        # 1) process exits
        for rank, p in procs.items():
            rc = p.poll()
            if rc is None or self._stopping:
                continue
            self._on_death(
                rank,
                reason=(
                    f"exited rc={rc}" if rc >= 0
                    else f"killed by signal {-rc}"
                ),
                clean=(rc == 0),
                pending_ready=pending_ready,
            )
        # 2) heartbeat staleness (wedged-but-alive replicas)
        try:
            beats = _fleet.read_heartbeats(
                self.rendezvous_dir, self.run_id
            )
        except OSError:  # pragma: no cover - transient fs wobble
            beats = {}
        now = time.time()
        for rank, rec in beats.items():
            with self._lock:
                p = self._procs.get(rank)
            if p is None or p.poll() is not None or rec.get("stopped"):
                continue
            if rec.get("pid") != p.pid:
                # a PREVIOUS incarnation's beat still on disk: the
                # respawned replica has not published yet (still
                # importing jax) — judging the stale beat against
                # the new process would kill every restart of a
                # heartbeat-detected death in an endless loop
                continue
            age = now - float(rec.get("ts", now))
            if age > self.heartbeat_timeout_s:
                logger.error(
                    "serving fleet: replica %d heartbeat stale "
                    "%.2fs — killing", rank, age,
                )
                try:
                    p.kill()
                    p.wait(timeout=10)
                except Exception:  # pragma: no cover - best effort
                    pass
                self._on_death(
                    rank,
                    reason=f"heartbeat stale {age:.2f}s",
                    clean=False, pending_ready=pending_ready,
                )
        # 3) restarted replicas turning ready: record the warm
        # report (the zero-compile-restart evidence)
        for rank, t_restart in list(pending_ready.items()):
            snap = self.router.replicas().get(rank)
            if snap and snap["state"] == "running" \
                    and snap["attempt"] == self._attempts.get(rank):
                pending_ready.pop(rank)
                report = {
                    "recovery_s": round(
                        time.monotonic() - t_restart, 3
                    ),
                    "attempt": snap["attempt"],
                    **snap.get("process", {}),
                }
                self.restart_reports[rank] = report
                _flight.record(
                    "router.replica_restarted", rank=rank, **report
                )
                if (report.get("xla_compiles", 0) or 0) > 0:
                    # the shared-store contract broke: a restarted
                    # replica should warm purely from store hits
                    logger.warning(
                        "serving fleet: restarted replica %d "
                        "performed %d XLA compiles (warm store "
                        "should have made this 0)", rank,
                        report["xla_compiles"],
                    )
        if self.degraded and not budget_exhausted_logged:
            budget_exhausted_logged = True
            logger.error(
                "serving fleet: restart budget exhausted — "
                "continuing degraded on survivors"
            )
        return budget_exhausted_logged

    def _on_death(self, rank: int, *, reason: str, clean: bool,
                  pending_ready: Dict[int, float]) -> None:
        """One replica died: cut it from routing NOW, then respawn it
        (crash restarts draw from the budget; clean exits — a drained
        replica, the rolling-restart flow — respawn for free)."""
        if self._stopping:
            # a watcher iteration that outlived stop()'s bounded join
            # must not spawn an orphan replica into a torn-down fleet
            return
        self.router.mark_dead(rank, reason)
        _fleet.DEAD_RANKS.inc()
        _flight.record(
            "router.replica_exit", rank=rank, reason=reason, clean=clean,
        )
        logger.warning(
            "serving fleet: replica %d down (%s)%s", rank, reason,
            " [clean]" if clean else "",
        )
        if clean:
            # a clean exit only earns the budget-free respawn when
            # this incarnation actually REACHED readiness (the router
            # saw it running) — the rolling-restart flow. A cmd that
            # exits 0 without ever serving is crash-looping in
            # disguise and would otherwise respawn ~1/poll_s forever,
            # budget-free. Readiness, not wall-clock: a drain right
            # after a fast startup is still a legitimate clean retire.
            snap = self.router.replicas().get(rank)
            served = bool(
                snap
                and snap.get("attempt") == self._attempts.get(rank)
                and snap.get("ever_running")
            )
            if not served:
                logger.warning(
                    "serving fleet: replica %d exited clean without "
                    "ever becoming ready — charging the restart budget",
                    rank,
                )
                clean = False
        with self._lock:
            # the death is accounted NOW: leaving the dead Popen in
            # _procs would re-detect the same exit on every poll and
            # (if _spawn below fails transiently) re-charge the budget
            # for one death until it was exhausted
            self._procs.pop(rank, None)
        if not clean:
            if self.restarts >= self.max_restarts:
                self.degraded = True
                return
            self.restarts += 1
            m.ROUTER_REPLICA_RESTARTS.inc()
        pending_ready[rank] = time.monotonic()
        try:
            self._spawn(rank)
        except Exception as e:
            # transient fork failure (ENOMEM/EAGAIN, a user cmd(rank)
            # hiccup): the budget is already charged for THIS death —
            # retry the spawn with backoff instead of losing the rank
            logger.error(
                "serving fleet: respawn of replica %d failed (%s) — "
                "will retry", rank, e,
            )
            self._respawn_pending[rank] = time.monotonic() + 1.0

    # -- shutdown -----------------------------------------------------------

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Retire the fleet: drain every replica over HTTP (graceful —
        queued work completes), wait for clean exits, escalate SIGTERM
        → SIGKILL for stragglers, then stop the router and ingress."""
        self._stopping = True
        if self._watcher is not None:
            self._watcher.join(timeout=self.poll_s * 4 + 2.0)
            self._watcher = None
        bound = self.grace_s if timeout is None else timeout
        with self._lock:
            procs = dict(self._procs)
        if drain:
            cards = read_cards(self.rendezvous_dir, self.run_id)
            # drain CONCURRENTLY: the POSTs are independent, and a
            # wedged sidecar must cost one 2s timeout total, not 2s
            # per wedged replica serialized into every stop()
            drainers = [
                threading.Thread(
                    target=http_json,
                    args=(card_addr(card), "POST", "/admin/drain",
                          {}, 2.0),
                    daemon=True, name=f"tfs-fleet-drain-{rank}",
                )
                for rank, p in procs.items()
                if p.poll() is None
                and (card := cards.get(rank)) is not None
            ]
            for t in drainers:
                t.start()
            for t in drainers:
                t.join(timeout=2.5)
        deadline = time.monotonic() + bound
        while time.monotonic() < deadline and any(
            p.poll() is None for p in procs.values()
        ):
            time.sleep(0.02)
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and any(
            p.poll() is None for p in procs.values()
        ):
            time.sleep(0.02)
        for p in procs.values():
            if p.poll() is None:  # pragma: no cover - wedged in IO
                p.kill()
        exit_codes = {r: p.wait() for r, p in procs.items()}
        self.router.stop()  # also shuts the ingress httpd down
        self._ingress = None
        _flight.record(
            "router.fleet_stop", exit_codes=exit_codes,
            restarts=self.restarts,
        )
        logger.info(
            "serving fleet stopped (restarts=%d, exits=%s)",
            self.restarts, exit_codes,
        )

    def kill_replica(self, rank: int,
                     sig: int = signal.SIGKILL) -> Optional[int]:
        """Chaos helper: signal one replica (default ``kill -9``) —
        the supervision loop detects, reroutes, and restarts it.
        Returns the killed pid (None when the rank is not running)."""
        with self._lock:
            p = self._procs.get(rank)
        if p is None or p.poll() is not None:
            return None
        pid = p.pid
        os.kill(pid, sig)
        return pid

    def status(self) -> dict:
        return {
            "replicas": self.router.replicas(),
            "live": self.router.live_count(),
            "restarts": self.restarts,
            "degraded": self.degraded,
            "restart_reports": dict(self.restart_reports),
            "router": self.router.counters(),
        }

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)
