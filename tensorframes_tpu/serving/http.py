"""Thin HTTP adapter over :class:`~tensorframes_tpu.serving.Server`.

The in-process future API is the real surface; this adapter exists so a
sidecar/load-generator can speak to a server without linking Python —
the same daemon-thread ``ThreadingHTTPServer`` shape as
``observability.metrics_server`` (one file, stdlib only, no framework).

Routes:

* ``POST /v1/<endpoint>`` — body ``{"inputs": {col: value|nested list},
  "deadline_s": float?}``; each handler thread blocks on its request's
  future (the batcher coalesces across concurrent handlers — the
  threaded server IS the concurrency source). Replies
  ``{"outputs": {...}, "rows": n, "latency_s": ...}``.
* ``GET /healthz`` — ``Server.stats()`` (running flag, endpoints,
  queue depths, admission counters).

Status mapping keeps the failure taxonomy visible to load balancers:
400 malformed/validation, 404 unknown endpoint, 429 ``queue_full`` /
``too_large`` (backpressure shed — retry with backoff), 503 ``closed``
(draining/stopped), 504 deadline expired, 500 dispatch error.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ..utils import get_logger
from ..validation import ValidationError
from .batcher import DeadlineExceededError, RejectedError
from .server import Server, UnknownEndpointError

logger = get_logger(__name__)

__all__ = ["serve_http"]


def serve_http(server: Server, port: int = 0, addr: str = "127.0.0.1",
               request_timeout_s: Optional[float] = None):
    """Serve ``server`` over HTTP from a daemon thread. ``port=0``
    binds an ephemeral port — read it back from
    ``httpd.server_address[1]``. Returns the ``ThreadingHTTPServer``;
    call ``.shutdown()`` to stop (drain the :class:`Server` itself
    separately — the adapter owns no lifecycle)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] in ("/", "/healthz"):
                self._reply(200, server.stats())
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802 - http.server API
            path = self.path.split("?")[0]
            if not path.startswith("/v1/"):
                self._reply(404, {"error": "not found"})
                return
            endpoint = path[len("/v1/"):]
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    raise TypeError(
                        f"body must be a JSON object, got "
                        f"{type(req).__name__}"
                    )
                inputs = req.get("inputs")
                deadline_s = req.get("deadline_s")
            except (ValueError, TypeError) as e:
                self._reply(400, {"error": f"malformed request: {e}"})
                return
            t0 = time.perf_counter()
            try:
                fut = server.submit(endpoint, inputs,
                                    deadline_s=deadline_s)
            except UnknownEndpointError as e:
                self._reply(404, {"error": str(e)})
                return
            except ValidationError as e:
                self._reply(400, {"error": str(e)})
                return
            except RejectedError as e:
                self._reply(
                    503 if e.reason == "closed" else 429,
                    {"error": str(e), "reason": e.reason},
                )
                return
            except (ValueError, TypeError) as e:
                # submit()'s own argument errors (e.g. deadline_s <= 0)
                # are client faults; a dispatch-time ValueError raised
                # through fut.result() below is NOT — it takes the 500
                # path so clients/load balancers see a server error
                self._reply(400, {"error": str(e)})
                return
            try:
                outs = fut.result(request_timeout_s)
            except RejectedError as e:
                self._reply(
                    503 if e.reason == "closed" else 429,
                    {"error": str(e), "reason": e.reason},
                )
                return
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e)})
                return
            except Exception as e:  # dispatch failure: the 500 class
                logger.warning("serving http dispatch error: %s", e)
                self._reply(
                    500, {"error": f"{type(e).__name__}: {e}"}
                )
                return
            self._reply(200, {
                "outputs": {k: v.tolist() for k, v in outs.items()},
                "rows": next(iter(outs.values())).shape[0] if outs else 0,
                "latency_s": round(time.perf_counter() - t0, 6),
            })

        def log_message(self, *args):  # load generators must not spam
            pass

    import threading

    httpd = ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(
        target=httpd.serve_forever, daemon=True, name="tfs-serving-http"
    )
    t.start()
    return httpd
