"""Thin, hardened HTTP adapter over :class:`~tensorframes_tpu.serving.Server`.

The in-process future API is the real surface; this adapter exists so a
sidecar/load-generator/fleet router can speak to a server without
linking Python — the same daemon-thread ``ThreadingHTTPServer`` shape as
``observability.metrics_server`` (one file, stdlib only, no framework).

Routes:

* ``POST /v1/<endpoint>`` — body ``{"inputs": {col: value|nested list},
  "deadline_s": float?, "idempotency_key": str?}``; each handler thread
  blocks on its request's future (the batcher coalesces across
  concurrent handlers — the threaded server IS the concurrency source).
  Replies ``{"outputs": {...}, "rows": n, "latency_s": ...}``. The
  idempotency key rides straight into ``Server.submit`` — a redriven
  dispatch joins the original future instead of re-executing.
* ``GET /healthz`` — ``Server.stats()``: the lifecycle ``state``
  (``starting|running|draining|stopped``), queue depths, admission
  counters, and process compile counters — everything the fleet router
  scrapes.
* ``POST /admin/drain`` — triggers ``Server.drain()`` (admission
  closes, queued work completes) and replies 202 with the state; the
  rolling-restart hook. Poll ``/healthz`` for ``draining`` →
  ``stopped``.

Status mapping keeps the failure taxonomy visible to load balancers:
400 malformed/validation, 404 unknown endpoint, 408 read timeout, 413
body over the ingress limit, 429 ``queue_full`` / ``too_large``
(backpressure shed — retry with backoff), 503 ``closed``
(draining/stopped) or connection bound reached, 504 deadline expired,
500 dispatch error.

Ingress hardening (ISSUE 13): the transport sheds BEFORE admission —
request bodies over ``max_body_bytes`` get 413, a connection whose read
stalls past ``read_timeout_s`` is closed (408 when a reply is still
possible), and connections beyond ``max_connections`` get an immediate
503 — each counted by reason in
``tftpu_serving_rejections_total{reason=}``. Bounded the same way the
batcher's queue is: overload sheds with a counted refusal, never an
unbounded buffer or a hang.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Optional

from ..observability import context as _context
from ..utils import get_logger
from ..validation import ValidationError
from .batcher import DeadlineExceededError, RejectedError
from .server import Server, UnknownEndpointError
from . import metrics as m

logger = get_logger(__name__)

__all__ = [
    "serve_http", "make_hardened_http_server", "read_bounded_body",
    "reply_json", "parse_json_object",
    "DEFAULT_MAX_BODY_BYTES", "DEFAULT_READ_TIMEOUT_S",
    "DEFAULT_MAX_CONNECTIONS",
]

#: Ingress defaults: generous for row-batch JSON, bounded for a server
#: that must survive a misbehaving client.
DEFAULT_MAX_BODY_BYTES = 8 << 20
DEFAULT_READ_TIMEOUT_S = 30.0
DEFAULT_MAX_CONNECTIONS = 128

_CONN_LIMIT_BODY = json.dumps({
    "error": "concurrent connection limit reached — retry with backoff",
    "reason": "conn_limit",
}).encode()
_CONN_LIMIT_RESPONSE = (
    b"HTTP/1.1 503 Service Unavailable\r\n"
    b"Content-Type: application/json\r\n"
    b"Content-Length: " + str(len(_CONN_LIMIT_BODY)).encode() + b"\r\n"
    b"Connection: close\r\n\r\n" + _CONN_LIMIT_BODY
)


def reply_json(handler, code: int, payload: dict) -> None:
    """Write one JSON response on a ``BaseHTTPRequestHandler`` — the
    shared reply shape of the server sidecar and the router ingress."""
    body = json.dumps(payload).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def parse_json_object(handler, raw: bytes) -> Optional[dict]:
    """Parse a request body that must be a JSON object; on anything
    else replies 400 and returns None (shared 400 taxonomy of the
    sidecar and the router ingress)."""
    try:
        req = json.loads(raw or b"{}")
        if not isinstance(req, dict):
            raise TypeError(
                f"body must be a JSON object, got {type(req).__name__}"
            )
        return req
    except (ValueError, TypeError) as e:
        handler._reply(400, {"error": f"malformed request: {e}"})
        return None


def read_bounded_body(handler, max_body_bytes: int,
                      read_timeout_s: Optional[float]) -> Optional[bytes]:
    """Read ``handler``'s request body under the ingress bounds
    (shared by the server sidecar and the fleet router's ingress).
    Returns the raw bytes, or ``None`` when a hardening refusal already
    replied (413 over the byte limit, 408 on a stalled read, 400 on a
    malformed Content-Length) and marked the connection for close —
    each counted in ``tftpu_serving_rejections_total{reason=}``."""
    try:
        length = int(handler.headers.get("Content-Length", 0) or 0)
    except (TypeError, ValueError):
        length = -1
    if length < 0:
        handler._reply(400, {"error": "malformed Content-Length"})
        handler.close_connection = True
        return None
    if length > max_body_bytes:
        m.http_rejected("body_too_large").inc()
        handler._reply(413, {
            "error": (
                f"request body of {length} bytes exceeds the "
                f"ingress limit of {max_body_bytes}"
            ),
            "reason": "body_too_large",
        })
        # the unread body is still in flight: close instead of
        # draining an attacker's megabytes to reuse the socket
        handler.close_connection = True
        return None
    try:
        return handler.rfile.read(length)
    except TimeoutError:  # socket.timeout alias: stalled read
        m.http_rejected("read_timeout").inc()
        handler.close_connection = True
        try:
            handler._reply(408, {
                "error": (
                    f"connection read stalled past {read_timeout_s:g}s"
                ),
                "reason": "read_timeout",
            })
        except OSError:  # pragma: no cover - peer already gone
            pass
        return None


def _reject_conn(server, request, slots) -> None:
    """Send the raw conn-limit 503 and close, off the accept thread.
    The drain of the client's unread request bytes (closing with data
    still buffered RSTs the socket, which can discard the 503 before
    the client reads it) is bounded by a TOTAL deadline — a trickling
    peer cannot pin this thread past it. ``slots`` bounds how many of
    these threads exist at once (released here)."""
    try:
        request.settimeout(0.5)
        request.sendall(_CONN_LIMIT_RESPONSE)
        request.shutdown(socket.SHUT_WR)
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline and request.recv(65536):
            pass
    except OSError:
        pass
    finally:
        try:
            server.close_request(request)
        except OSError:  # pragma: no cover - already closed
            pass
        slots.release()


def make_hardened_http_server(addr, handler_cls, max_connections: int):
    """Build a ``ThreadingHTTPServer`` with a concurrent-connection
    bound (and a bounded reject path). A factory function so the
    ``http.server`` import stays inside the serving path, matching
    ``serve_http``."""
    from http.server import ThreadingHTTPServer

    class _Bounded(ThreadingHTTPServer):
        daemon_threads = True

        def __init__(self, server_address, RequestHandlerClass):
            super().__init__(server_address, RequestHandlerClass)
            self._conn_lock = threading.Lock()
            self._active_conns = 0
            self.max_connections = int(max_connections)
            self._reject_slots = threading.BoundedSemaphore(8)

        def process_request(self, request, client_address):
            with self._conn_lock:
                admit = self._active_conns < self.max_connections
                if admit:
                    self._active_conns += 1
            if not admit:
                # shed at the accept edge with a raw 503. The
                # send/drain runs on a short-lived daemon thread:
                # process_request executes ON the accept loop, and
                # a peer trickling bytes (or a slow send) must
                # never stall accepts for the whole server — that
                # would let one client past the cap take down
                # healthz scrapes too
                m.http_rejected("conn_limit").inc()
                # the reject path is bounded too: a connection
                # flood past the cap must not spawn more reject
                # threads than the cap allows for real work — past
                # the reject budget, just close (still counted)
                if self._reject_slots.acquire(blocking=False):
                    threading.Thread(
                        target=_reject_conn,
                        args=(self, request, self._reject_slots),
                        daemon=True, name="tfs-http-conn-reject",
                    ).start()
                else:
                    self.shutdown_request(request)
                return
            try:
                super().process_request(request, client_address)
            except BaseException:
                # the handler thread never started (thread
                # exhaustion — the very overload this cap guards):
                # its finally-decrement will never run, and a
                # leaked slot here would ratchet the counter to
                # the cap and 503 every future connection forever
                with self._conn_lock:
                    self._active_conns -= 1
                raise

        def process_request_thread(self, request, client_address):
            try:
                super().process_request_thread(request, client_address)
            finally:
                with self._conn_lock:
                    self._active_conns -= 1

        def handle_error(self, request, client_address):
            # a peer dropping mid-request (kill -9 chaos, impatient
            # client) is normal operation here — no stderr traceback
            import sys

            exc = sys.exc_info()[1]
            if isinstance(exc, (ConnectionError, TimeoutError)):
                return
            super().handle_error(request, client_address)

    return _Bounded(addr, handler_cls)


def serve_http(server: Server, port: int = 0, addr: str = "127.0.0.1",
               request_timeout_s: Optional[float] = None,
               max_body_bytes: int = DEFAULT_MAX_BODY_BYTES,
               read_timeout_s: Optional[float] = DEFAULT_READ_TIMEOUT_S,
               max_connections: int = DEFAULT_MAX_CONNECTIONS):
    """Serve ``server`` over HTTP from a daemon thread. ``port=0``
    binds an ephemeral port — read it back from
    ``httpd.server_address[1]``. Returns the ``ThreadingHTTPServer``;
    call ``.shutdown()`` to stop (drain the :class:`Server` itself
    separately — the adapter owns no lifecycle, though ``POST
    /admin/drain`` lets remote operators trigger one). Hardening knobs:
    ``max_body_bytes`` (413 past it), ``read_timeout_s`` (per-connection
    socket timeout; ``None`` disables), ``max_connections`` (immediate
    503 past the concurrent bound) — refusals counted in
    ``tftpu_serving_rejections_total{reason=}``."""
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # StreamRequestHandler applies this to the connection socket:
        # a client that stalls mid-read (slowloris body, dead peer)
        # cannot pin a handler thread forever
        timeout = read_timeout_s

        def _reply(self, code: int, payload: dict) -> None:
            reply_json(self, code, payload)

        def _read_body(self) -> Optional[bytes]:
            return read_bounded_body(self, max_body_bytes, read_timeout_s)

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] in ("/", "/healthz"):
                self._reply(200, server.stats())
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802 - http.server API
            path = self.path.split("?")[0]
            if path == "/admin/drain":
                body = self._read_body()
                if body is None:
                    return
                server.drain()
                self._reply(202, {"state": server.state})
                return
            if not path.startswith("/v1/"):
                self._reply(404, {"error": "not found"})
                return
            endpoint = path[len("/v1/"):]
            raw = self._read_body()
            if raw is None:
                return
            req = parse_json_object(self, raw)
            if req is None:
                return
            inputs = req.get("inputs")
            deadline_s = req.get("deadline_s")
            idem_key = req.get("idempotency_key")
            # cross-hop trace adoption (ISSUE 17): the router's stamped
            # request id binds to this handler thread, so the submit →
            # batcher slot → flush spans carry the SAME id the router's
            # ingress span does — `observability merge` joins them into
            # one cross-process request timeline
            trace_id, _ = _context.parse_trace_header(
                self.headers.get(_context.TRACE_HEADER)
            )
            if trace_id:
                m.REQUEST_TRACE.inc()
            t0 = time.perf_counter()
            try:
                with _context.request_scope(trace_id):
                    fut = server.submit(endpoint, inputs,
                                        deadline_s=deadline_s,
                                        idempotency_key=idem_key)
            except UnknownEndpointError as e:
                self._reply(404, {"error": str(e)})
                return
            except ValidationError as e:
                self._reply(400, {"error": str(e)})
                return
            except RejectedError as e:
                self._reply(
                    503 if e.reason == "closed" else 429,
                    {"error": str(e), "reason": e.reason},
                )
                return
            except (ValueError, TypeError) as e:
                # submit()'s own argument errors (e.g. deadline_s <= 0)
                # are client faults; a dispatch-time ValueError raised
                # through fut.result() below is NOT — it takes the 500
                # path so clients/load balancers see a server error
                self._reply(400, {"error": str(e)})
                return
            try:
                outs = fut.result(request_timeout_s)
            except RejectedError as e:
                self._reply(
                    503 if e.reason == "closed" else 429,
                    {"error": str(e), "reason": e.reason},
                )
                return
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e)})
                return
            except Exception as e:  # dispatch failure: the 500 class
                logger.warning("serving http dispatch error: %s", e)
                self._reply(
                    500, {"error": f"{type(e).__name__}: {e}"}
                )
                return
            # query-endpoint tables may carry object-dtype key columns
            # (string group keys) alongside dense arrays — .tolist()
            # serializes both; len() covers any non-ndarray stragglers
            self._reply(200, {
                "outputs": {
                    k: (v.tolist() if hasattr(v, "tolist") else list(v))
                    for k, v in outs.items()
                },
                "rows": len(next(iter(outs.values()))) if outs else 0,
                "latency_s": round(time.perf_counter() - t0, 6),
            })

        def log_message(self, *args):  # load generators must not spam
            pass

    httpd = make_hardened_http_server(
        (addr, port), Handler, max_connections
    )
    t = threading.Thread(
        target=httpd.serve_forever, daemon=True, name="tfs-serving-http"
    )
    t.start()
    return httpd
