"""The in-process async serving front: ``Server.submit()`` → futures.

The stack below this module is batch-shaped: verbs dispatch whole
frames, the AOT store + ``warmup()`` make cold starts free, and fused
Programs run an entire pipeline per dispatch (PRs 5/7). This module is
the latency-shaped consumer the ROADMAP's north star needs: admit
single-row/small-batch requests against a registered Program (or verb
chain), coalesce them with the continuous batcher, dispatch through the
EXISTING executor (one ``run_rows_bucketed`` per flush — the same
per-shape AOT executables every verb uses), and scatter per-request
results back with padding-row masking.

Zero-steady-state-compile contract: ``start()`` warms every endpoint
over :func:`~tensorframes_tpu.compilecache.serving_row_buckets`
(the power-of-two ladder ``ServingConfig.max_batch_rows`` bounds —
the SAME policy the batcher pads flushes into), so every flush lands on
a warmed AOT key: with a persistent store armed, a fresh process
serves its first request without a single XLA compile.

Lifecycle: ``start()`` (warm + spin batchers) → ``submit()``/``call()``
→ ``stop(drain=True)`` (admission closes with counted rejections,
queued work completes, workers join). ``Server`` is also a context
manager; per-request ``deadline_s`` follows ``RetryPolicy.deadline_s``
semantics (total elapsed wall-clock — resilience/retry.py), and an
optional server-wide :class:`~tensorframes_tpu.resilience.RetryPolicy`
retries transient dispatch failures (XLA programs are pure, hence
idempotent — the safe case for retry).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import context as _context
from ..observability import flight as _flight
from ..program import Program
from ..resilience.retry import RetryPolicy, retry_call
from ..shape import Unknown
from ..utils import get_logger
from ..validation import ValidationError
from .batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    RejectedError,
    ResultFuture,
    ServingError,
)
from . import metrics as m

logger = get_logger(__name__)

__all__ = [
    "ServingConfig", "Endpoint", "Server",
    "ServingError", "RejectedError", "DeadlineExceededError",
    "UnknownEndpointError",
]


class UnknownEndpointError(ValidationError):
    """``submit()`` to an endpoint name that was never registered.

    A distinct type (not a message substring) so the HTTP adapter can
    map it to 404 without misclassifying a feed-validation error whose
    message happens to mention an endpoint."""


@dataclasses.dataclass
class ServingConfig:
    """Admission/coalescing knobs, per server.

    ``max_batch_rows`` — flush when pending rows reach this (also the
    largest admissible single request and the top of the warmed bucket
    ladder). ``max_latency_s`` — the flush timer: the oldest queued
    request never waits longer than this before its batch dispatches.
    ``max_queue_rows`` — admission bound; past it ``submit`` raises
    :class:`RejectedError` (``reason=queue_full``) instead of queueing
    unboundedly. ``default_deadline_s`` — deadline applied when a
    request does not carry its own (None = no deadline).
    ``warmup`` — precompile the bucket ladder at ``start()``.
    """

    max_batch_rows: int = 64
    max_latency_s: float = 0.005
    max_queue_rows: int = 4096
    default_deadline_s: Optional[float] = None
    donate: bool = False
    warmup: bool = True
    #: bound of the idempotency-key dedup cache (completed and in-flight
    #: futures a redriven submit can join instead of re-executing);
    #: 0 disables dedup entirely. Entries also expire after
    #: ``idempotency_ttl_s`` — dedup exists for the redrive window
    #: (seconds), and a completed future pins its RESULT arrays, so a
    #: count-only bound would hold the last N responses in memory
    #: indefinitely under steady load.
    idempotency_cache: int = 4096
    idempotency_ttl_s: float = 60.0


class Endpoint:
    """One registered program: feed validation + the coalesced dispatch
    the batcher calls. Inputs are CELL-shaped (the map_rows convention):
    a request's feeds carry a leading request-rows dim on every column
    (a bare cell is accepted as one row)."""

    def __init__(self, name: str, program: Program, donate: bool,
                 retry: Optional[RetryPolicy]):
        self.name = name
        self.program = program
        self.compiled = program.compiled()
        self._donate = donate
        self._retry = retry

    def validate_feeds(self, feeds) -> Dict[str, np.ndarray]:
        """Normalize one request's feeds: name set must match the
        program's inputs exactly, dtypes cast to the input specs (the
        same boundary cast ``gather_feeds`` applies), cell dims checked
        against the spec, bare cells promoted to one row. Returns dense
        arrays sharing one lead dim."""
        if not isinstance(feeds, dict) or not feeds:
            raise ValidationError(
                f"endpoint {self.name!r}: feeds must be a non-empty "
                "dict of column name -> array"
            )
        want = set(self.program.input_names)
        got = set(feeds)
        if got != want:
            missing = sorted(want - got)
            extra = sorted(got - want)
            raise ValidationError(
                f"endpoint {self.name!r}: feeds {sorted(got)} do not "
                f"match program inputs {sorted(want)}"
                + (f"; missing {missing}" if missing else "")
                + (f"; unexpected {extra}" if extra else "")
            )
        out: Dict[str, np.ndarray] = {}
        lead: Optional[int] = None
        lead_of: Optional[str] = None
        for spec in self.program.inputs:
            try:
                arr = np.asarray(feeds[spec.name],
                                 dtype=spec.dtype.np_dtype)
            except (TypeError, ValueError) as e:
                raise ValidationError(
                    f"endpoint {self.name!r}: feed {spec.name!r} does "
                    f"not convert to {spec.dtype.name}: {e}"
                ) from None
            cell = list(spec.shape.dims)
            if arr.ndim == len(cell):
                arr = arr[None]  # bare cell = one row
            if arr.ndim != len(cell) + 1:
                raise ValidationError(
                    f"endpoint {self.name!r}: feed {spec.name!r} has "
                    f"rank {arr.ndim}, expected cell rank {len(cell)} "
                    f"(one row) or {len(cell) + 1} (rows-leading batch)"
                )
            for got_d, want_d in zip(arr.shape[1:], cell):
                if want_d != Unknown and int(got_d) != int(want_d):
                    raise ValidationError(
                        f"endpoint {self.name!r}: feed {spec.name!r} "
                        f"cell shape {tuple(arr.shape[1:])} does not "
                        f"match spec {tuple(cell)}"
                    )
            if lead is None:
                lead, lead_of = int(arr.shape[0]), spec.name
            elif int(arr.shape[0]) != lead:
                raise ValidationError(
                    f"endpoint {self.name!r}: feed {spec.name!r} has "
                    f"{arr.shape[0]} rows but {lead_of!r} has {lead} — "
                    "every column of one request must share the lead dim"
                )
            out[spec.name] = arr
        if lead == 0:
            raise ValidationError(
                f"endpoint {self.name!r}: zero-row request"
            )
        return out

    def dispatch(self, feeds: Dict[str, np.ndarray],
                 rows: int) -> Dict[str, np.ndarray]:
        """One coalesced flush through the executor's bucket-ladder
        entry, under the server's retry policy (pure program ⇒
        idempotent ⇒ safe to retry)."""
        return retry_call(
            self.compiled.run_rows_bucketed, feeds,
            donate=self._donate,
            policy=self._retry,
            describe=f"serving.dispatch[{self.name}]",
        )


class Server:
    """The serving front: register endpoints, ``start()``, ``submit()``.

    ``register()`` accepts an analyzed :class:`Program` (cell-shaped
    inputs — what ``tfs.compile_program(fetches, frame, block=False)``
    returns), or any map_rows-style fetches (DSL nodes / a python
    function) plus a frame/schema to normalize against.
    """

    def __init__(self, config: Optional[ServingConfig] = None,
                 retry: Optional[RetryPolicy] = None):
        from ..compilecache import serving_row_buckets

        self.config = config or ServingConfig()
        # checked for warmup=False servers too: flushes above the
        # ladder dispatch at exact shapes no warmup can ever cover, so
        # the zero-steady-state-compile contract silently breaks.
        # serving_row_buckets owns the refusal (ONE bucket policy,
        # stated once) — the result is discarded, only the bound check
        # matters here
        serving_row_buckets(self.config.max_batch_rows)
        self._retry = retry
        self._endpoints: Dict[str, Endpoint] = {}
        self._batchers: Dict[str, ContinuousBatcher] = {}
        self._decode: Dict[str, object] = {}  # name -> DecodeEngine
        self._queries: Dict[str, object] = {}  # name -> QueryEndpoint
        self._lock = threading.Lock()
        self._running = False
        self._starting = False
        self._draining = False
        self._stop_requested = False
        # idempotency-key dedup (ISSUE 13): (endpoint, key) ->
        # (ResultFuture, inserted_at), FIFO eviction at
        # config.idempotency_cache plus TTL expiry. A router redriving
        # a request (or any client retrying with the same key) joins
        # the original future instead of executing the program twice.
        # Scoped per endpoint: the same client key against a different
        # endpoint is a different operation, never a cache hit.
        self._idem: "collections.OrderedDict[tuple, tuple]" = (
            collections.OrderedDict()
        )
        self.warmup_reports: Dict[str, object] = {}

    # -- registration -------------------------------------------------------

    def register(self, name: str, fetches, frame_or_schema=None,
                 feed_dict=None) -> Endpoint:
        """Register ``fetches`` as endpoint ``name``. Non-Program
        fetches need ``frame_or_schema`` (a TensorFrame or Schema) to
        resolve column dtypes/cell shapes, exactly like ``map_rows``."""
        from ..ops.verbs import _apply_feed_dict, _normalize_program

        if not name or "/" in name:
            raise ValueError(
                f"endpoint name must be non-empty and '/'-free, "
                f"got {name!r}"
            )
        schema = getattr(frame_or_schema, "schema", frame_or_schema)
        if not (isinstance(fetches, Program) and fetches.outputs) \
                and schema is None:
            raise ValueError(
                "register() needs frame_or_schema to normalize "
                "non-Program fetches (or pass a compile_program result)"
            )
        program, _ = _normalize_program(
            fetches, schema, block=False, feed_dict=feed_dict
        )
        program = _apply_feed_dict(program, feed_dict)
        for spec in program.inputs:
            if any(d == Unknown for d in spec.shape.dims):
                # a non-lead Unknown cell dim breaks both serving
                # contracts at once: two admissible requests with
                # different concrete extents poison each other's
                # np.concatenate at flush time, and even homogeneous
                # flushes dispatch at shapes no warmup ladder covers
                raise ValueError(
                    f"endpoint {name!r}: input {spec.name!r} has "
                    f"cell shape {tuple(spec.shape.dims)} with an "
                    "Unknown dim — serving endpoints need concrete "
                    "cell shapes (only the row/lead dim may vary); "
                    "pad or split the column to a fixed extent"
                )
        ep = Endpoint(name, program, self.config.donate, self._retry)
        with self._lock:
            if name in self._endpoints or name in self._decode:
                raise ValueError(f"endpoint {name!r} already registered")
            self._endpoints[name] = ep
            batcher = ContinuousBatcher(
                name, ep.dispatch,
                max_batch_rows=self.config.max_batch_rows,
                max_latency_s=self.config.max_latency_s,
                max_queue_rows=self.config.max_queue_rows,
            )
            self._batchers[name] = batcher
            # _starting counts as live: a register racing start()'s
            # warm loop must warm its own endpoint (start() snapshotted
            # the endpoint list before warming, but its final loop
            # starts EVERY batcher — an unwarmed one would silently
            # break the zero-steady-state-compile contract)
            live = self._running or self._starting
        if live:
            # late registration on a live server: warm OUTSIDE the lock
            # (a multi-second compile must not block submissions), then
            # start the batcher only if no concurrent stop() won
            if self.config.warmup:
                try:
                    self.warmup_reports[name] = self._warm(ep)
                except BaseException:
                    # a failed warm must not leave a zombie behind: its
                    # batcher would never start (every submit sheds as
                    # 'closed') and the name could never be
                    # re-registered with a fixed program
                    with self._lock:
                        self._endpoints.pop(name, None)
                        self._batchers.pop(name, None)
                    # start()'s final loop may have started this
                    # batcher while we warmed (register during
                    # _starting): stop it so its worker/expirer threads
                    # don't outlive the rollback (no-op if never
                    # started; queued futures fail loudly)
                    batcher.stop(drain=False)
                    raise
            with self._lock:
                if self._running:
                    batcher.start()
        return ep

    def register_decode(self, name: str, model_cfg, params,
                        decode_config=None):
        """Register an iterative decode endpoint (ISSUE 11): a
        :class:`~tensorframes_tpu.serving.DecodeEngine` over
        ``model_cfg``/``params`` with a paged int8 KV pool.
        ``submit(name, {"prompt": tokens})`` resolves to
        ``{"tokens": [1, max_new_tokens]}`` when the LAST token lands
        (streaming-final semantics — the HTTP sidecar replies once, at
        sequence completion); the rejection/deadline taxonomy matches
        flush endpoints (429 shed, 504 on slot-wait expiry). The engine
        has its own admission queue and scheduler — it shares the
        server's lifecycle, default deadline, and ``stats()`` surface,
        not the flush batcher's coalescing."""
        from .decode import DecodeConfig, DecodeEngine

        if not name or "/" in name:
            raise ValueError(
                f"endpoint name must be non-empty and '/'-free, "
                f"got {name!r}"
            )
        cfg = decode_config or DecodeConfig()
        if cfg.default_deadline_s is None:
            cfg = dataclasses.replace(
                cfg, default_deadline_s=self.config.default_deadline_s
            )
        cfg = dataclasses.replace(
            cfg, warmup=cfg.warmup and self.config.warmup
        )
        engine = DecodeEngine(name, model_cfg, params, cfg)
        with self._lock:
            if name in self._endpoints or name in self._decode:
                raise ValueError(f"endpoint {name!r} already registered")
            self._decode[name] = engine
            live = self._running or self._starting
        if live:
            # late registration on a live server: warm + spin the
            # engine outside the lock; a failed start must not leave a
            # zombie name behind (same rollback contract as register())
            try:
                engine.start()
            except BaseException:
                with self._lock:
                    self._decode.pop(name, None)
                engine.stop(drain=False)
                raise
        return engine

    def register_query(self, name: str, source, build):
        """Register a relational pipeline as endpoint ``name`` (ISSUE
        20): ``source`` is a :class:`~tensorframes_tpu.serving.query.
        QuerySource` (a scan directory or a frame), ``build`` maps the
        source frame to a lazy verb chain. ``submit(name, {})`` answers
        with the pipeline's result over the source's CURRENT contents,
        fronted by the (plan fingerprint × content digest) result cache
        with counted invalidation; algebraic scan-rooted aggregates
        refresh incrementally (only new chunks re-read/re-executed,
        bit-identical to full recompute). Registration probes the plan
        EAGERLY — a broken build fn or empty source fails here, not on
        the first request — and records TFG114 evidence when the plan
        declines either cache level."""
        from .query import QueryEndpoint, QuerySource

        if not name or "/" in name:
            raise ValueError(
                f"endpoint name must be non-empty and '/'-free, "
                f"got {name!r}"
            )
        if not isinstance(source, QuerySource):
            raise ValueError(
                f"register_query() needs a QuerySource, "
                f"got {type(source).__name__}"
            )
        with self._lock:
            if name in self._endpoints or name in self._decode \
                    or name in self._queries:
                raise ValueError(f"endpoint {name!r} already registered")
        # probe OUTSIDE the lock (it reads a chunk and traces the plan);
        # the name was only reserved by the check above, so a concurrent
        # duplicate is caught again on insert
        q = QueryEndpoint(name, source, build)
        with self._lock:
            if name in self._endpoints or name in self._decode \
                    or name in self._queries:
                raise ValueError(f"endpoint {name!r} already registered")
            self._queries[name] = q
            live = self._running or self._starting
        if live:
            # late registration on a live server: warm outside the lock,
            # same rollback contract as register() — a failed warm must
            # not leave a zombie name (or stale TFG114 evidence) behind
            if self.config.warmup:
                try:
                    self.warmup_reports[name] = q.warm()
                except BaseException:
                    from .query import _withdraw_events

                    with self._lock:
                        self._queries.pop(name, None)
                    _withdraw_events(name)
                    raise
            with self._lock:
                if self._running:
                    q.open()
        return q

    def endpoints(self) -> List[str]:
        with self._lock:
            return sorted(
                set(self._endpoints) | set(self._decode)
                | set(self._queries)
            )

    def _warm(self, ep: Endpoint):
        """Precompile (or disk-load) the endpoint's bucket ladder so the
        first flush is already a jit-cache hit — warmup-from-serving-
        config, sharing the batcher's exact bucket policy."""
        from ..compilecache import serving_row_buckets, warm_program

        report = warm_program(
            ep.program,
            rows=serving_row_buckets(self.config.max_batch_rows),
            block=False,
            donate=self.config.donate,
        )
        logger.info("serving warmup[%s]: %s", ep.name, report.counts())
        return report

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Server":
        with self._lock:
            if self._running or self._starting:
                return self
            self._starting = True
            eps = list(self._endpoints.values())
            engines = list(self._decode.values())
            queries = list(self._queries.values())
        t0 = time.perf_counter()
        try:
            if self.config.warmup:
                for ep in eps:
                    self.warmup_reports[ep.name] = self._warm(ep)
                # query endpoints warm by executing once: the first
                # request is then a cache hit, and with a persistent
                # result store armed a RESTARTED process warms from the
                # store without re-reading a single chunk
                for q in queries:
                    self.warmup_reports[q.name] = q.warm()
            # decode engines warm their slot × phase bucket grid inside
            # their own start() — still in the warm phase, so the
            # running flag only flips once every endpoint is hot
            for eng in engines:
                eng.start()
        finally:
            with self._lock:
                self._starting = False
        with self._lock:
            if self._stop_requested:
                # a stop() arrived mid-warmup: it wins. Leave admission
                # closed — opening the batchers here would silently
                # undo a shutdown the caller believes already happened
                self._stop_requested = False
                _flight.record(
                    "serving.start_aborted",
                    endpoints=sorted(self._endpoints),
                    warmup_s=round(time.perf_counter() - t0, 6),
                )
                for eng in engines:
                    eng.stop(drain=True)
                return self
            # batchers open BEFORE the running flag flips: healthz must
            # never say running=true while submits would shed as
            # 'closed' — during warmup the server honestly reports
            # running=false, so load balancers keep traffic away until
            # admission is actually open
            for b in self._batchers.values():
                b.start()
            for q in self._queries.values():
                q.open()
            self._running = True
        _flight.record(
            "serving.start", endpoints=self.endpoints(),
            warmup_s=round(time.perf_counter() - t0, 6),
            max_batch_rows=self.config.max_batch_rows,
            max_latency_s=self.config.max_latency_s,
        )
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Close admission and shut the batchers down. ``drain=True``
        (the graceful default) completes every queued request first;
        ``drain=False`` fails them with :class:`ServingError`. New
        submissions during and after shutdown get a COUNTED rejection
        (``reason=closed``), never a hang. While a graceful stop is
        completing queued work, :attr:`state` reads ``draining`` —
        routers and load balancers read ONE lifecycle source of truth."""
        try:
            with self._lock:
                if self._starting:
                    # stop() during start()'s warm loop: record the
                    # request so start() leaves admission closed instead
                    # of opening the batchers after this stop() returned
                    self._stop_requested = True
                if not self._running and not self._batchers \
                        and not self._decode and not self._queries:
                    return
                self._running = False
                if drain:
                    self._draining = True
                batchers = list(self._batchers.values())
                engines = list(self._decode.values())
                queries = list(self._queries.values())
            # query endpoints execute synchronously in the submitting
            # thread — closing admission IS the drain (no queue to
            # complete, no worker to join)
            for q in queries:
                q.close()
            pending = sum(b.queued_rows for b in batchers)
            _flight.record(
                "serving.drain" if drain else "serving.stop",
                endpoints=self.endpoints(), queued_rows=pending,
            )
            for b in batchers:
                b.stop(drain=drain, timeout=timeout)
            for eng in engines:
                eng.stop(drain=drain, timeout=timeout)
        finally:
            with self._lock:
                self._draining = False
                # a stopped server keeps no dedup state: the cached
                # futures pin result arrays, and nothing can redrive
                # into a closed admission anyway
                self._idem.clear()

    def drain(self, wait: bool = False,
              timeout: Optional[float] = None) -> None:
        """Gracefully retire this server: close admission (new submits
        shed with counted ``closed`` rejections), complete every queued
        request, then read ``state == "stopped"``. The rolling-restart
        primitive — externally triggerable as ``POST /admin/drain`` on
        the HTTP sidecar, so an operator (or the fleet router) can
        drain a replica without linking Python. ``wait=False`` (the
        HTTP-friendly default) returns immediately; poll
        :attr:`state`/healthz for ``draining`` → ``stopped``."""
        if wait:
            self.stop(drain=True, timeout=timeout)
            return
        with self._lock:
            if self._draining:
                return  # one drain is already completing the queue
            self._draining = True
        threading.Thread(
            target=self.stop, kwargs={"drain": True, "timeout": timeout},
            daemon=True, name="tfs-serving-drain",
        ).start()

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(drain=True)

    @property
    def running(self) -> bool:
        return self._running

    @property
    def state(self) -> str:
        """The lifecycle state, ONE source of truth for routers and
        operators: ``starting`` (warmup in progress, admission still
        closed), ``running`` (admission open), ``draining`` (admission
        closed, queued work completing), ``stopped`` (admission closed,
        nothing in flight). ``running == (state == "running")``."""
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._starting:
            return "starting"
        if self._draining:
            return "draining"
        if self._running:
            return "running"
        return "stopped"

    # -- request path -------------------------------------------------------

    def submit(self, endpoint: str, feeds,
               deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None) -> ResultFuture:
        """Admit one request; returns a :class:`ResultFuture` resolving
        to this request's rows of every program output. Raises
        :class:`RejectedError` on backpressure/closed/oversize (never
        blocks admission), :class:`ValidationError` on malformed feeds.

        ``idempotency_key`` deduplicates retried dispatches: a second
        submit carrying a key this server has already admitted joins
        the ORIGINAL request's future (counted by
        ``tftpu_serving_idempotent_dedup_total``) instead of executing
        the program again — the fleet router stamps every dispatch with
        one so a redrive after a replica failure can never
        double-execute on a replica that already accepted it."""
        if idempotency_key is not None and self.config.idempotency_cache:
            ikey = (endpoint, str(idempotency_key))
            now = time.monotonic()
            with self._lock:
                self._prune_idem_locked(now)
                entry = self._idem.get(ikey)
            if entry is not None:
                m.IDEMPOTENT_DEDUP.inc()
                _flight.record(
                    "serving.idempotent_dedup", endpoint=endpoint,
                    key=str(idempotency_key),
                )
                return entry[0]
        # request id for cross-hop tracing (ISSUE 17): the thread-bound
        # id (the HTTP adapter binds the X-Tftpu-Trace header's id
        # before calling submit) wins; otherwise the idempotency key —
        # a router-stamped dispatch stays traceable even through an
        # in-process submit path that never touched the HTTP adapter
        trace_id = _context.current_request()
        if trace_id is None and idempotency_key is not None:
            trace_id = str(idempotency_key)
        fut = self._submit_new(endpoint, feeds, deadline_s, trace_id)
        if idempotency_key is not None and self.config.idempotency_cache:
            with self._lock:
                # first-writer-wins: a racing duplicate that also missed
                # the cache keeps ITS future (both executed — the race
                # window is one admission; the router never races itself)
                self._idem.setdefault(ikey, (fut, time.monotonic()))
                while len(self._idem) > self.config.idempotency_cache:
                    self._idem.popitem(last=False)
        return fut

    def _prune_idem_locked(self, now: float) -> None:
        """Expire dedup entries past the TTL (FIFO order == insertion
        order, so expired entries are a prefix). A completed future
        pins its result arrays — dedup only needs to cover the redrive
        window, not steady-state history."""
        ttl = self.config.idempotency_ttl_s
        if ttl is None or ttl <= 0:
            return
        while self._idem:
            _, (_, inserted) = next(iter(self._idem.items()))
            if now - inserted <= ttl:
                break
            self._idem.popitem(last=False)

    def _submit_new(self, endpoint: str, feeds,
                    deadline_s: Optional[float],
                    trace_id: Optional[str] = None) -> ResultFuture:
        eng = self._decode.get(endpoint)
        if eng is not None:
            # iterative decode rides the engine's own admission queue
            # (its expirer covers slot waits); the engine inherited the
            # server default deadline at register time
            with _context.request_scope(trace_id):
                return eng.submit(feeds, deadline_s=deadline_s)
        q = self._queries.get(endpoint)
        if q is not None:
            # registered queries execute synchronously under the
            # endpoint lock: a cache hit's latency IS the lookup, and
            # there is no batch to coalesce (the input is the source's
            # current contents, not the request's feeds)
            with _context.request_scope(trace_id):
                return q.submit(feeds, deadline_s=deadline_s,
                                trace_id=trace_id)
        try:
            ep = self._endpoints[endpoint]
        except KeyError:
            raise UnknownEndpointError(
                f"unknown endpoint {endpoint!r}; registered: "
                f"{self.endpoints()}"
            ) from None
        arrs = ep.validate_feeds(feeds)
        rows = int(next(iter(arrs.values())).shape[0])
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (got {deadline_s}) — the same "
                "contract as RetryPolicy.deadline_s"
            )
        return self._batchers[endpoint].offer(arrs, rows, deadline_s,
                                              trace_id=trace_id)

    def call(self, endpoint: str, feeds,
             deadline_s: Optional[float] = None,
             timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        """Synchronous convenience: ``submit(...).result(...)``."""
        return self.submit(endpoint, feeds, deadline_s).result(timeout)

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Queue depths + THIS server's admission counters, for health
        endpoints. Summed from the per-batcher counters — the registry's
        ``tftpu_serving_*`` series are process-wide, so a fresh server
        (or one of several in a process) must not report a sibling's
        traffic as its own."""
        with self._lock:
            batchers = dict(self._batchers)
            engines = dict(self._decode)
            queries = dict(self._queries)
            running = self._running
            state = self._state_locked()
            # TTL-prune the idempotency cache here too: healthz is
            # scraped continuously by routers, so expiry does not
            # depend on further KEYED submits arriving (the cache must
            # not pin the last burst's results after traffic stops)
            self._prune_idem_locked(time.monotonic())
        queues: Dict[str, int] = {}
        decode: Dict[str, Dict[str, int]] = {}
        latency: Dict[str, Dict[str, float]] = {}
        totals = {
            "admitted_requests": 0,
            "admitted_rows": 0,
            "rejected": {r: 0 for r in m.REJECT_REASONS},
            "deadline_expired": 0,
        }

        def _tally(name, snap):
            queues[name] = snap["queued_rows"]
            totals["admitted_requests"] += snap["admitted_requests"]
            totals["admitted_rows"] += snap["admitted_rows"]
            for r, c in snap["rejected"].items():
                totals["rejected"][r] += c
            totals["deadline_expired"] += snap["deadline_expired"]
            # per-endpoint p50/p95/p99 (ISSUE 17): endpoint cardinality
            # stays out of the metrics registry (TFL003), so the
            # quantiles ride healthz/stats() instead — each batcher
            # keeps its own in-object histogram
            if snap.get("latency"):
                latency[name] = snap["latency"]

        for name, b in batchers.items():
            _tally(name, b.counters())
        # registered queries (ISSUE 20): admission counters tally like
        # any endpoint; the result-cache rows ride a dedicated section
        # (per-endpoint cardinality stays out of the registry, TFL003 —
        # the process-wide tftpu_result_cache_* series carry the totals)
        query_rows: Dict[str, Dict[str, object]] = {}
        for name, q in queries.items():
            _tally(name, q.counters())
            query_rows[name] = q.cache_stats()
        for name, eng in engines.items():
            snap = eng.counters()
            _tally(name, snap)
            decode[name] = {
                "running_slots": snap["running_slots"],
                "free_pages": snap["free_pages"],
                # KV memory hierarchy (ISSUE 19): swap engagement and
                # prefix-cache hit rate per engine, 0 when unarmed
                "allocatable_pages": snap["allocatable_pages"],
                "shared_pages": snap["shared_pages"],
                "swap_outs": snap["swap_outs"],
                "swap_resumes": snap["swap_resumes"],
                "swap_fallbacks": snap["swap_fallbacks"],
                "prefix_hits": snap["prefix_hits"],
                "prefix_misses": snap["prefix_misses"],
            }
        out = {
            "running": running,
            "state": state,
            "endpoints": sorted(queues),
            "queued_rows": queues,
            "latency": latency,
            **totals,
            # process-wide compile accounting, for the fleet's
            # zero-compile-restart assertion: a restarted replica warmed
            # from the shared store must report xla_compiles == 0 with
            # compile_cache_hits > 0 over its healthz (these ARE the
            # process-global registry series — deliberately, unlike the
            # per-server admission counters above)
            "process": _process_compile_counters(),
        }
        if decode:
            out["decode"] = decode
        if query_rows:
            out["queries"] = query_rows
        return out


def _process_compile_counters() -> Dict[str, int]:
    """XLA-compile and compile-store counters (instruments acquired at
    import below — the executor/compilecache registered them first; the
    same acquisition pattern the fleet supervisor uses for
    tftpu_fleet_*)."""
    return {
        "xla_compiles": int(_COMPILE_SECONDS.count),
        "compile_cache_hits": int(_STORE_HITS.value),
        "compile_cache_misses": int(_STORE_MISSES.value),
    }


# Acquired (get-or-create by name) at import: ops/executor.py and
# compilecache/store.py register these before the serving package loads
# (package __init__ order), so these are the SAME instruments — healthz
# reports the process's real compile accounting, and the registrations
# stay at import time (TFL003).
from ..observability.metrics import counter as _acquire_counter  # noqa: E402
from ..observability.metrics import histogram as _acquire_histogram  # noqa: E402

_COMPILE_SECONDS = _acquire_histogram("tftpu_executor_compile_seconds")
_STORE_HITS = _acquire_counter("tftpu_compilecache_hits_total")
_STORE_MISSES = _acquire_counter("tftpu_compilecache_misses_total")
