"""Block-paged KV cache pool: fixed-size pages, per-sequence page tables.

The memory manager half of the iterative decode engine (ISSUE 11,
vLLM-style). Device state is the columnar pool from
``models.generation.init_paged_kv`` — int8 k/v plus f32 per-slot scales,
page-major ``[num_pages, layers, heads, page_size, head_dim]`` — so the
pool IS a set of frame columns with pages as rows (:meth:`as_frame`
materializes the TensorFrame view; ROADMAP #3's data plane can later
back these columns with its block store). This class owns the HOST side:
the free list, per-sequence page ownership, and the page tables the
step functions gather through.

Accounting contract (property-swept in tests/test_decode.py): every
page except the reserved null page 0 is at all times EITHER free OR
owned by exactly one sequence — ``alloc`` can never hand out an owned
page, ``free_seq`` can never double-free, and :meth:`check` asserts the
partition after any interleaving of join/extend/evict. Page 0 belongs
to nobody: padding slots and masked prefill positions write their
garbage there, and the attention masks guarantee it is never read
unmasked.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

__all__ = ["PagedKVPool", "PoolAccountingError", "PoolExhaustedError"]


class PoolAccountingError(RuntimeError):
    """A page alloc/free invariant was violated (double free, freeing a
    page the sequence does not own, or a corrupted free list) — always
    a bug in the caller or the pool, never load-dependent."""


class PoolExhaustedError(RuntimeError):
    """``alloc`` asked for more pages than are free. The decode engine
    turns this into preemption (evict a victim, retry), never an
    unbounded wait."""


class PagedKVPool:
    """Fixed-size KV pages + per-sequence page tables over the columnar
    pool state. ``columns`` holds the device arrays (reassigned by the
    engine after every functional step); everything else is host-side
    bookkeeping under the engine's scheduling thread (single-threaded
    by design — the pool is not itself locked)."""

    def __init__(self, cfg, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        from ..models.generation import init_paged_kv

        if max_pages_per_seq < 1:
            raise ValueError(
                f"max_pages_per_seq must be >= 1, got {max_pages_per_seq}"
            )
        if num_pages < 1 + max_pages_per_seq:
            # the null page plus one full sequence horizon is the floor:
            # below it the OLDEST running sequence could page-fault with
            # nothing left to evict — the livelock the forward-progress
            # guarantee exists to rule out
            raise ValueError(
                f"num_pages={num_pages} cannot hold the null page plus "
                f"one full sequence ({max_pages_per_seq} pages) — an "
                "undersized pool could stall its own oldest sequence; "
                "raise num_pages or lower the decode horizon"
            )
        self.cfg = cfg
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.columns: Dict[str, object] = init_paged_kv(
            cfg, self.num_pages, self.page_size
        )
        self._free: collections.deque = collections.deque(
            range(1, self.num_pages)
        )
        self._owned: Dict[int, List[int]] = {}
        self._closed = False
        # the free-pages gauge aggregates by DELTA across live pools
        # (several decode endpoints share one process-wide series; a
        # set() here would clobber the siblings)
        from . import metrics as m

        m.DECODE_FREE_PAGES.inc(len(self._free))

    # -- capacity -----------------------------------------------------------

    @property
    def usable_pages(self) -> int:
        """Allocatable pages (everything but the null page)."""
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    def pages_needed(self, n_positions: int) -> int:
        """Pages covering ``n_positions`` KV slots."""
        return -(-int(n_positions) // self.page_size)

    # -- alloc / free -------------------------------------------------------

    def alloc(self, seq: int, n: int) -> List[int]:
        """Give ``n`` pages to sequence ``seq`` (appended to its table).
        Raises :class:`PoolExhaustedError` when fewer than ``n`` are
        free (nothing is partially allocated)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc of {n} pages")
        held = self._owned.setdefault(int(seq), [])
        if len(held) + n > self.max_pages_per_seq:
            raise PoolAccountingError(
                f"sequence {seq} would hold {len(held) + n} pages, "
                f"over max_pages_per_seq={self.max_pages_per_seq}"
            )
        if n > len(self._free):
            raise PoolExhaustedError(
                f"need {n} pages, {len(self._free)} free "
                f"(of {self.usable_pages} usable)"
            )
        got = [self._free.popleft() for _ in range(n)]
        held.extend(got)
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.dec(n)
        return got

    def free_seq(self, seq: int) -> int:
        """Return every page owned by ``seq`` to the free list; returns
        the count (0 for a sequence holding nothing). Double frees and
        corrupted ownership raise :class:`PoolAccountingError`."""
        pages = self._owned.pop(int(seq), None)
        if pages is None:
            return 0
        free_set = set(self._free)
        for p in pages:
            if p in free_set or p == 0:
                self._owned[int(seq)] = pages  # restore for postmortem
                raise PoolAccountingError(
                    f"double free: page {p} of sequence {seq} is "
                    "already free (or the null page)"
                )
        self._free.extend(pages)
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(pages))
        return len(pages)

    def owned(self, seq: int) -> List[int]:
        return list(self._owned.get(int(seq), ()))

    def table(self, seq: int) -> np.ndarray:
        """The sequence's page table as the step functions expect it:
        int32 ``[max_pages_per_seq]``, unused tail entries = null page 0."""
        t = np.zeros(self.max_pages_per_seq, np.int32)
        pages = self._owned.get(int(seq), ())
        t[:len(pages)] = pages
        return t

    def null_table(self) -> np.ndarray:
        """An all-null page table — what padding slots carry."""
        return np.zeros(self.max_pages_per_seq, np.int32)

    def close(self) -> None:
        """Withdraw this pool's contribution from the process-wide
        free-pages gauge (the engine calls it at stop). Accounting and
        ``check()`` keep working; only the gauge stops tracking."""
        if not self._closed:
            self._closed = True
            from . import metrics as m

            m.DECODE_FREE_PAGES.dec(len(self._free))

    def reopen(self) -> None:
        """Re-enroll in the free-pages gauge (engine restart)."""
        if self._closed:
            self._closed = False
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(self._free))

    # -- invariants ---------------------------------------------------------

    def check(self) -> None:
        """Assert the accounting partition: free ∪ owned = pages 1..P-1,
        with no page in two places. Cheap; the property sweep calls it
        after every mutation."""
        free = list(self._free)
        free_set = set(free)
        if len(free) != len(free_set):
            raise PoolAccountingError("free list holds a duplicate page")
        owned_all: List[int] = []
        for seq, pages in self._owned.items():
            if len(pages) > self.max_pages_per_seq:
                raise PoolAccountingError(
                    f"sequence {seq} holds {len(pages)} pages > "
                    f"max_pages_per_seq={self.max_pages_per_seq}"
                )
            owned_all.extend(pages)
        owned_set = set(owned_all)
        if len(owned_all) != len(owned_set):
            raise PoolAccountingError(
                "a page is owned by two sequences (or twice by one)"
            )
        if free_set & owned_set:
            raise PoolAccountingError(
                f"pages both free and owned: {sorted(free_set & owned_set)}"
            )
        want = set(range(1, self.num_pages))
        have = free_set | owned_set
        if have != want:
            raise PoolAccountingError(
                f"leaked pages: {sorted(want - have)}; "
                f"phantom pages: {sorted(have - want)}"
            )

    # -- host-swap tier (ROADMAP #3 data plane) ------------------------------

    def spill(self, store) -> Dict[str, object]:
        """Snapshot the whole pool into a
        :class:`~tensorframes_tpu.blockstore.BlockStore`: the device
        columns land as ONE spilled block (explicitly pushed to disk —
        a pool snapshot is cold by definition, it must not consume the
        store's resident budget) plus the host bookkeeping (free list,
        ownership) in the returned snapshot dict. This is the KV pool's
        host-swap tier: a served model's KV state survives an engine
        restart through the same CRC-checked segments frame blocks
        spill to, and :meth:`restore` brings it back bit-identically.
        Per-sequence swap (evict one sequence's pages to host instead
        of recompute-replay) remains the named follow-up."""
        block = {k: np.asarray(v) for k, v in self.columns.items()}
        ref = store.put(block)
        store.spill(ref)
        return {
            "ref": ref,
            "free": list(self._free),
            "owned": {int(s): list(p) for s, p in self._owned.items()},
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "max_pages_per_seq": self.max_pages_per_seq,
        }

    def restore(self, store, snapshot: Dict[str, object]) -> None:
        """Rehydrate pool state from a :meth:`spill` snapshot:
        CRC-checked reload of the column block (corruption raises
        ``BlockCorruptionError`` — counted + quarantined by the store,
        never silently served), ``device_put`` back to the default
        device, and the page accounting restored exactly. Geometry
        mismatches raise before anything is touched."""
        import jax

        for field in ("num_pages", "page_size", "max_pages_per_seq"):
            if int(snapshot[field]) != int(getattr(self, field)):
                raise PoolAccountingError(
                    f"restore into a pool with different {field}: "
                    f"snapshot {snapshot[field]}, pool {getattr(self, field)}"
                )
        block = store.get(snapshot["ref"])
        if set(block) != set(self.columns):
            raise PoolAccountingError(
                f"snapshot columns {sorted(block)} != pool columns "
                f"{sorted(self.columns)}"
            )
        new_cols = {
            k: jax.device_put(np.asarray(v)) for k, v in block.items()
        }
        old_free = len(self._free)
        self.columns = new_cols
        self._free = collections.deque(int(p) for p in snapshot["free"])
        self._owned = {
            int(s): [int(p) for p in pages]
            for s, pages in dict(snapshot["owned"]).items()
        }
        self.check()
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(self._free) - old_free)

    # -- frame view ---------------------------------------------------------

    def as_frame(self):
        """The pool as a TensorFrame (one row per page, one column per
        pool array) — a materialized snapshot view for the data plane /
        debugging, not a live alias."""
        from ..frame import frame_from_arrays

        return frame_from_arrays(
            {k: np.asarray(v) for k, v in self.columns.items()},
            num_blocks=1,
        )

    def __repr__(self) -> str:  # pragma: no cover - convenience
        return (
            f"PagedKVPool(pages={self.num_pages}, "
            f"page_size={self.page_size}, free={self.num_free}, "
            f"seqs={len(self._owned)})"
        )
