"""Block-paged KV cache pool: fixed-size pages, per-sequence page tables.

The memory manager half of the iterative decode engine (ISSUE 11,
vLLM-style). Device state is the columnar pool from
``models.generation.init_paged_kv`` — int8 k/v plus f32 per-slot scales,
page-major ``[num_pages, layers, heads, page_size, head_dim]`` — so the
pool IS a set of frame columns with pages as rows (:meth:`as_frame`
materializes the TensorFrame view; ROADMAP #3's data plane can later
back these columns with its block store). This class owns the HOST side:
the free list, per-sequence page ownership, the page tables the step
functions gather through, and (ISSUE 19) the two extra page lifecycles
of the serving KV memory hierarchy:

* **shared prefix pages** — read-only pages published into a
  content-addressed index (hash chain over page-granular token
  prefixes) with per-page refcounts. A sequence whose prompt prefix
  matches a published chain references those pages instead of
  re-prefilling them; a page whose refcount drops to 0 stays cached
  (LRU) until :meth:`alloc` reclaims it under demand.
* **host-swapped sequences** — :meth:`swap_out_seq` moves one evicted
  sequence's page payloads into a
  :class:`~tensorframes_tpu.blockstore.BlockStore` segment (CRC +
  quarantine machinery included) and :meth:`swap_in_seq` brings them
  back, so preemption resume restores pages instead of recomputing.

Accounting contract (property-swept in tests/test_decode.py): every
page except the reserved null page 0 is at all times in EXACTLY ONE of
three states — free, exclusively owned by one sequence, or shared with
a refcount — and :meth:`check` asserts the three-way partition after
any interleaving of join/extend/evict/share/copy-on-extend/swap. Page 0
belongs to nobody: padding slots and masked prefill positions write
their garbage there, and the attention masks guarantee it is never read
unmasked.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PagedKVPool", "PoolAccountingError", "PoolExhaustedError"]


class PoolAccountingError(RuntimeError):
    """A page alloc/free invariant was violated (double free, freeing a
    page the sequence does not own, or a corrupted free list) — always
    a bug in the caller or the pool, never load-dependent."""


class PoolExhaustedError(RuntimeError):
    """``alloc`` asked for more pages than are free. The decode engine
    turns this into preemption (evict a victim, retry), never an
    unbounded wait."""


def _chain_key(prev: bytes, tokens: np.ndarray) -> bytes:
    """One link of the page-granular content address: the hash of a
    page's tokens chained onto the hash of everything before it, so a
    key identifies the page's tokens AND its whole prefix lineage."""
    h = hashlib.sha1(prev)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class PagedKVPool:
    """Fixed-size KV pages + per-sequence page tables over the columnar
    pool state. ``columns`` holds the device arrays (reassigned by the
    engine after every functional step); everything else is host-side
    bookkeeping under the engine's scheduling thread (single-threaded
    by design — the pool is not itself locked)."""

    def __init__(self, cfg, num_pages: int, page_size: int,
                 max_pages_per_seq: int):
        from ..models.generation import init_paged_kv

        if max_pages_per_seq < 1:
            raise ValueError(
                f"max_pages_per_seq must be >= 1, got {max_pages_per_seq}"
            )
        if num_pages < 1 + max_pages_per_seq:
            # the null page plus one full sequence horizon is the floor:
            # below it the OLDEST running sequence could page-fault with
            # nothing left to evict — the livelock the forward-progress
            # guarantee exists to rule out
            raise ValueError(
                f"num_pages={num_pages} cannot hold the null page plus "
                f"one full sequence ({max_pages_per_seq} pages) — an "
                "undersized pool could stall its own oldest sequence; "
                "raise num_pages or lower the decode horizon"
            )
        self.cfg = cfg
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.columns: Dict[str, object] = init_paged_kv(
            cfg, self.num_pages, self.page_size
        )
        self._free: collections.deque = collections.deque(
            range(1, self.num_pages)
        )
        self._owned: Dict[int, List[int]] = {}
        # -- prefix-cache state (shared read-only pages, ISSUE 19) ----------
        # a sequence's table is refs (shared prefix chain) + owned
        # (exclusive pages), in position order
        self._refs: Dict[int, List[int]] = {}
        self._shared_ref: Dict[int, int] = {}        # page -> refcount
        self._shared_lru: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()                # refcount-0 pages
        )
        self._prefix_index: Dict[bytes, int] = {}    # chain key -> page
        # page -> (parent chain key, own chain key, page tokens)
        self._prefix_meta: Dict[int, Tuple[bytes, bytes, bytes]] = {}
        self._prefix_children: Dict[bytes, List[int]] = {}
        self._closed = False
        # the free-pages gauge aggregates by DELTA across live pools
        # (several decode endpoints share one process-wide series; a
        # set() here would clobber the siblings)
        from . import metrics as m

        m.DECODE_FREE_PAGES.inc(len(self._free))

    # -- capacity -----------------------------------------------------------

    @property
    def usable_pages(self) -> int:
        """Allocatable pages (everything but the null page)."""
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocatable(self) -> int:
        """Pages :meth:`alloc` can satisfy right now: the free list plus
        cached shared pages nobody references (reclaimable on demand).
        The engine's admission budget and preemption trigger read this —
        a cache full of refcount-0 pages must not starve admissions."""
        return len(self._free) + len(self._shared_lru)

    @property
    def num_shared(self) -> int:
        """Pages currently in the shared prefix cache (any refcount)."""
        return len(self._shared_ref)

    def pages_needed(self, n_positions: int) -> int:
        """Pages covering ``n_positions`` KV slots."""
        return -(-int(n_positions) // self.page_size)

    # -- alloc / free -------------------------------------------------------

    def alloc(self, seq: int, n: int) -> List[int]:
        """Give ``n`` exclusive pages to sequence ``seq`` (appended to
        its table after any shared prefix). Reclaims refcount-0 shared
        pages LRU-first when the free list alone cannot cover ``n``;
        raises :class:`PoolExhaustedError` when even that cannot
        (nothing is partially allocated)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc of {n} pages")
        held = self._owned.setdefault(int(seq), [])
        total = len(held) + len(self._refs.get(int(seq), ())) + n
        if total > self.max_pages_per_seq:
            raise PoolAccountingError(
                f"sequence {seq} would hold {total} pages, "
                f"over max_pages_per_seq={self.max_pages_per_seq}"
            )
        if n > len(self._free):
            self._reclaim_shared(n - len(self._free))
        if n > len(self._free):
            raise PoolExhaustedError(
                f"need {n} pages, {len(self._free)} free + "
                f"{len(self._shared_lru)} reclaimable "
                f"(of {self.usable_pages} usable)"
            )
        got = [self._free.popleft() for _ in range(n)]
        held.extend(got)
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.dec(n)
        return got

    def free_seq(self, seq: int) -> int:
        """Return every exclusive page owned by ``seq`` to the free list
        and drop its references on shared prefix pages (a shared page at
        refcount 0 stays cached until reclaimed). Returns the exclusive
        count freed (0 for a sequence holding nothing). Double frees and
        corrupted ownership raise :class:`PoolAccountingError`."""
        self._release_refs(int(seq))
        pages = self._owned.pop(int(seq), None)
        if pages is None:
            return 0
        free_set = set(self._free)
        for p in pages:
            if p in free_set or p == 0 or p in self._shared_ref:
                self._owned[int(seq)] = pages  # restore for postmortem
                raise PoolAccountingError(
                    f"double free: page {p} of sequence {seq} is "
                    "already free, shared, or the null page"
                )
        self._free.extend(pages)
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(pages))
        return len(pages)

    def owned(self, seq: int) -> List[int]:
        return list(self._owned.get(int(seq), ()))

    def seq_pages(self, seq: int) -> List[int]:
        """The sequence's full table in position order: shared prefix
        pages first, then its exclusive pages."""
        return (list(self._refs.get(int(seq), ()))
                + list(self._owned.get(int(seq), ())))

    def table(self, seq: int) -> np.ndarray:
        """The sequence's page table as the step functions expect it:
        int32 ``[max_pages_per_seq]``, unused tail entries = null page 0."""
        t = np.zeros(self.max_pages_per_seq, np.int32)
        pages = self.seq_pages(seq)
        t[:len(pages)] = pages
        return t

    def null_table(self) -> np.ndarray:
        """An all-null page table — what padding slots carry."""
        return np.zeros(self.max_pages_per_seq, np.int32)

    def close(self) -> None:
        """Withdraw this pool's contribution from the process-wide
        gauges (the engine calls it at stop). Accounting and ``check()``
        keep working; only the gauges stop tracking."""
        if not self._closed:
            self._closed = True
            from . import metrics as m

            m.DECODE_FREE_PAGES.dec(len(self._free))
            m.PREFIX_SHARED_PAGES.dec(len(self._shared_ref))

    def reopen(self) -> None:
        """Re-enroll in the process-wide gauges (engine restart)."""
        if self._closed:
            self._closed = False
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(self._free))
            m.PREFIX_SHARED_PAGES.inc(len(self._shared_ref))

    # -- content-addressed prefix cache (ISSUE 19) --------------------------

    def prefix_match(
        self, tokens: np.ndarray
    ) -> Tuple[List[int], int, Optional[int], int]:
        """Longest published chain matching ``tokens``' page-granular
        prefix. Returns ``(pages, covered, cow_page, cow_tokens)``:
        ``pages`` are the matched shared pages (covering ``covered``
        tokens), capped so at least one token is always left to compute
        (the engine needs the logits at the last prompt position, and
        computing them writes KV — never into a shared page).

        ``cow_page``, when not None, is a published page whose first
        ``cow_tokens`` tokens equal the ENTIRE remaining prompt tail —
        the copy-on-extend candidate: the caller copies it into a fresh
        exclusive page (:meth:`copy_on_extend`) and teacher-forces only
        the final token, instead of prefilling the tail."""
        tokens = np.asarray(tokens, np.int32)
        plen = int(tokens.shape[0])
        ps = self.page_size
        limit = max(0, (plen - 1) // ps)
        pages: List[int] = []
        key = b""
        for i in range(limit):
            nxt = _chain_key(key, tokens[i * ps:(i + 1) * ps])
            page = self._prefix_index.get(nxt)
            if page is None:
                break
            pages.append(page)
            key = nxt
        covered = len(pages) * ps
        tail = tokens[covered:]
        r = plen - covered
        cow = None
        if 0 < r <= ps:
            want = np.ascontiguousarray(tail, np.int32).tobytes()
            for cand in self._prefix_children.get(key, ()):
                if self._prefix_meta[cand][2][:len(want)] == want:
                    cow = cand
                    break
        return pages, covered, cow, r

    def prefix_acquire(self, seq: int, pages: List[int]) -> None:
        """Reference ``pages`` (a matched chain, in position order) as
        sequence ``seq``'s shared prefix. Must run before the sequence
        allocates any exclusive page (the table is refs-then-owned)."""
        seq = int(seq)
        if self._refs.get(seq) or self._owned.get(seq):
            raise PoolAccountingError(
                f"sequence {seq} already holds pages; a shared prefix "
                "must be acquired before any alloc"
            )
        if len(pages) > self.max_pages_per_seq:
            raise PoolAccountingError(
                f"prefix of {len(pages)} pages exceeds "
                f"max_pages_per_seq={self.max_pages_per_seq}"
            )
        for p in pages:
            if p not in self._shared_ref:
                raise PoolAccountingError(
                    f"page {p} is not in the shared prefix cache"
                )
            if self._shared_ref[p] == 0:
                self._shared_lru.pop(p, None)
            self._shared_ref[p] += 1
        self._refs[seq] = list(pages)

    def _release_refs(self, seq: int) -> None:
        for p in self._refs.pop(seq, ()):
            c = self._shared_ref.get(p)
            if c is None or c < 1:
                raise PoolAccountingError(
                    f"sequence {seq} released shared page {p} with "
                    f"refcount {c}"
                )
            self._shared_ref[p] = c - 1
            if c == 1:
                # unreferenced but still cached: future prompts can hit
                # it until alloc pressure reclaims LRU-first
                self._shared_lru[p] = None

    def publish_prefix(self, seq: int, tokens: np.ndarray) -> int:
        """Convert sequence ``seq``'s freshly prefilled FULL prompt
        pages into shared prefix-cache pages (the sequence keeps
        referencing them; its ragged tail page — decode writes land
        there — stays exclusive). Publishing stops at the first chain
        key already indexed by another lineage: the shared prefix must
        stay contiguous at the head of the table. Returns the number of
        pages published."""
        seq = int(seq)
        tokens = np.asarray(tokens, np.int32)
        ps = self.page_size
        refs = self._refs.setdefault(seq, [])
        owned = self._owned.get(seq, [])
        full = int(tokens.shape[0]) // ps
        key = b""
        for i in range(len(refs)):
            key = _chain_key(key, tokens[i * ps:(i + 1) * ps])
        published = 0
        for i in range(len(refs), full):
            if not owned:
                break
            page_toks = tokens[i * ps:(i + 1) * ps]
            nxt = _chain_key(key, page_toks)
            if nxt in self._prefix_index:
                break
            page = owned.pop(0)
            refs.append(page)
            self._shared_ref[page] = 1
            self._prefix_index[nxt] = page
            self._prefix_meta[page] = (
                key, nxt,
                np.ascontiguousarray(page_toks, np.int32).tobytes(),
            )
            self._prefix_children.setdefault(key, []).append(page)
            key = nxt
            published += 1
        if published and not self._closed:
            from . import metrics as m

            m.PREFIX_SHARED_PAGES.inc(published)
        return published

    def copy_on_extend(self, seq: int, src: int) -> int:
        """Allocate a fresh exclusive page for ``seq`` as the copy
        target of shared page ``src`` (the ragged-tail copy-on-extend:
        the caller copies the device payload, then writes freely into
        the copy). Pure accounting here — returns the destination page."""
        if src not in self._shared_ref:
            raise PoolAccountingError(
                f"copy-on-extend source page {src} is not shared"
            )
        return self.alloc(seq, 1)[0]

    def _reclaim_shared(self, n: int) -> int:
        """Evict up to ``n`` refcount-0 shared pages (LRU-first) back to
        the free list, unpublishing them from the content index."""
        evicted = 0
        while evicted < n and self._shared_lru:
            page, _ = self._shared_lru.popitem(last=False)
            if self._shared_ref.pop(page, 0) != 0:
                raise PoolAccountingError(
                    f"shared page {page} on the LRU with a live refcount"
                )
            parent, key, _toks = self._prefix_meta.pop(page)
            self._prefix_index.pop(key, None)
            kids = self._prefix_children.get(parent)
            if kids:
                try:
                    kids.remove(page)
                except ValueError:
                    pass
                if not kids:
                    del self._prefix_children[parent]
            self._free.append(page)
            evicted += 1
        if evicted and not self._closed:
            from . import metrics as m

            m.PREFIX_EVICTIONS.inc(evicted)
            m.PREFIX_SHARED_PAGES.dec(evicted)
            m.DECODE_FREE_PAGES.inc(evicted)
        return evicted

    # -- invariants ---------------------------------------------------------

    def check(self) -> None:
        """Assert the accounting partition: free ∪ exclusively-owned ∪
        shared-with-refcount = pages 1..P-1, with no page in two states,
        refcounts exactly matching the per-sequence references, and the
        content index bijective with the shared set. Cheap; the property
        sweep calls it after every mutation."""
        free = list(self._free)
        free_set = set(free)
        if len(free) != len(free_set):
            raise PoolAccountingError("free list holds a duplicate page")
        owned_all: List[int] = []
        for seq, pages in self._owned.items():
            held = len(pages) + len(self._refs.get(seq, ()))
            if held > self.max_pages_per_seq:
                raise PoolAccountingError(
                    f"sequence {seq} holds {held} pages > "
                    f"max_pages_per_seq={self.max_pages_per_seq}"
                )
            owned_all.extend(pages)
        owned_set = set(owned_all)
        if len(owned_all) != len(owned_set):
            raise PoolAccountingError(
                "a page is owned by two sequences (or twice by one)"
            )
        shared_set = set(self._shared_ref)
        counts: Dict[int, int] = {p: 0 for p in shared_set}
        for seq, pages in self._refs.items():
            for p in pages:
                if p not in shared_set:
                    raise PoolAccountingError(
                        f"sequence {seq} references page {p} which is "
                        "not in the shared set"
                    )
                counts[p] += 1
        for p, want in counts.items():
            if self._shared_ref[p] != want:
                raise PoolAccountingError(
                    f"shared page {p} refcount {self._shared_ref[p]} != "
                    f"{want} references held"
                )
        lru_set = set(self._shared_lru)
        zero_set = {p for p, c in self._shared_ref.items() if c == 0}
        if lru_set != zero_set:
            raise PoolAccountingError(
                f"LRU set {sorted(lru_set)} != refcount-0 shared pages "
                f"{sorted(zero_set)}"
            )
        index_pages = sorted(self._prefix_index.values())
        if index_pages != sorted(set(index_pages)):
            raise PoolAccountingError(
                "the prefix index maps two keys to one page"
            )
        if set(index_pages) != shared_set or set(
            self._prefix_meta
        ) != shared_set:
            raise PoolAccountingError(
                "prefix index/meta out of step with the shared set"
            )
        overlaps = (free_set & owned_set) | (free_set & shared_set) | (
            owned_set & shared_set
        )
        if overlaps:
            raise PoolAccountingError(
                f"pages in two partition states: {sorted(overlaps)}"
            )
        want = set(range(1, self.num_pages))
        have = free_set | owned_set | shared_set
        if have != want:
            raise PoolAccountingError(
                f"leaked pages: {sorted(want - have)}; "
                f"phantom pages: {sorted(have - want)}"
            )

    # -- host-swap tier (blockstore-backed, ISSUE 15 + 19) -------------------

    def spill(self, store, swaps: Optional[Dict[str, Dict]] = None,
              swap_store=None) -> Dict[str, object]:
        """Snapshot the whole pool into a
        :class:`~tensorframes_tpu.blockstore.BlockStore`: the device
        columns land as ONE spilled block (explicitly pushed to disk —
        a pool snapshot is cold by definition, it must not consume the
        store's resident budget) plus the host bookkeeping (free list,
        ownership) in the returned snapshot dict. This is the KV pool's
        whole-pool host-swap tier: a served model's KV state survives an
        engine restart through the same CRC-checked segments frame
        blocks spill to, and :meth:`restore` brings it back
        bit-identically. Per-sequence swap is :meth:`swap_out_seq`.

        ``swaps`` (PR 18 follow-up) folds per-sequence host-swap
        segments into the snapshot so they no longer die with the
        engine: a mapping of cross-restart identity (the request's
        trace id) → :meth:`swap_out_seq` snapshot. Each segment is
        CRC-check read from ``swap_store`` and re-published into
        ``store``; the manifest rides the snapshot's ``"swapped"`` key
        and :meth:`adopt_swapped` re-homes it into a fresh engine's
        swap store. A segment that comes back corrupt here is skipped
        (quarantined + counted by the store) — the sequence degrades
        to recompute-replay on redrive, never a wrong answer."""
        swapped: Dict[str, Dict] = {}
        if swaps and swap_store is not None:
            for tid, snap in dict(swaps).items():
                try:
                    seg = swap_store.get(snap["ref"])
                except Exception:
                    continue
                entry = {k: v for k, v in snap.items() if k != "ref"}
                entry["ref"] = store.put_spilled(seg)
                swapped[str(tid)] = entry
        block = {k: np.asarray(v) for k, v in self.columns.items()}
        ref = store.put(block)
        store.spill(ref)
        return {
            "swapped": swapped,
            "ref": ref,
            "free": list(self._free),
            "owned": {int(s): list(p) for s, p in self._owned.items()},
            # prefix-cache state rides the snapshot too — a restored
            # pool must keep every published page addressable
            "refs": {int(s): list(p) for s, p in self._refs.items()},
            "shared_ref": dict(self._shared_ref),
            "shared_lru": list(self._shared_lru),
            "prefix_index": dict(self._prefix_index),
            "prefix_meta": dict(self._prefix_meta),
            "prefix_children": {
                k: list(v) for k, v in self._prefix_children.items()
            },
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "max_pages_per_seq": self.max_pages_per_seq,
        }

    def restore(self, store, snapshot: Dict[str, object],
                swap_store=None) -> Dict[str, Dict]:
        """Rehydrate pool state from a :meth:`spill` snapshot:
        CRC-checked reload of the column block (corruption raises
        ``BlockCorruptionError`` — counted + quarantined by the store,
        never silently served), ``device_put`` back to the default
        device, and the page accounting restored exactly. Geometry
        mismatches raise before anything is touched. When the snapshot
        carries folded per-sequence swap segments and ``swap_store``
        is given, they are re-homed via :meth:`adopt_swapped` and the
        manifest is returned (``{}`` otherwise)."""
        import jax

        for field in ("num_pages", "page_size", "max_pages_per_seq"):
            if int(snapshot[field]) != int(getattr(self, field)):
                raise PoolAccountingError(
                    f"restore into a pool with different {field}: "
                    f"snapshot {snapshot[field]}, pool {getattr(self, field)}"
                )
        block = store.get(snapshot["ref"])
        if set(block) != set(self.columns):
            raise PoolAccountingError(
                f"snapshot columns {sorted(block)} != pool columns "
                f"{sorted(self.columns)}"
            )
        new_cols = {
            k: jax.device_put(np.asarray(v)) for k, v in block.items()
        }
        old_free = len(self._free)
        old_shared = len(self._shared_ref)
        self.columns = new_cols
        self._free = collections.deque(int(p) for p in snapshot["free"])
        self._owned = {
            int(s): [int(p) for p in pages]
            for s, pages in dict(snapshot["owned"]).items()
        }
        self._refs = {
            int(s): [int(p) for p in pages]
            for s, pages in dict(snapshot.get("refs", {})).items()
        }
        self._shared_ref = {
            int(p): int(c)
            for p, c in dict(snapshot.get("shared_ref", {})).items()
        }
        self._shared_lru = collections.OrderedDict(
            (int(p), None) for p in snapshot.get("shared_lru", ())
        )
        self._prefix_index = dict(snapshot.get("prefix_index", {}))
        self._prefix_meta = {
            int(p): tuple(v)
            for p, v in dict(snapshot.get("prefix_meta", {})).items()
        }
        self._prefix_children = {
            k: list(v)
            for k, v in dict(snapshot.get("prefix_children", {})).items()
        }
        self.check()
        if not self._closed:
            from . import metrics as m

            m.DECODE_FREE_PAGES.inc(len(self._free) - old_free)
            m.PREFIX_SHARED_PAGES.inc(len(self._shared_ref) - old_shared)
        return self.adopt_swapped(store, snapshot, swap_store)

    def adopt_swapped(self, store, snapshot: Dict[str, object],
                      swap_store) -> Dict[str, Dict]:
        """Re-home a :meth:`spill` snapshot's folded per-sequence swap
        segments into a live swap store WITHOUT touching pool page
        state: swapped sequences hold no pages (``swap_out_seq``
        released them), so they are the one part of an engine's KV
        state that is self-contained enough to move between engines.
        Returns ``{trace_id: swap-in snapshot}`` — the restored
        engine's parking manifest, consumed when each request is
        redriven. Corrupt segments are skipped (quarantined + counted
        by the store; the redrive degrades to recompute-replay)."""
        manifest: Dict[str, Dict] = {}
        if swap_store is None:
            return manifest
        for tid, entry in dict(snapshot.get("swapped", {})).items():
            try:
                seg = store.get(entry["ref"])
            except Exception:
                continue
            new = {k: v for k, v in entry.items() if k != "ref"}
            new["ref"] = swap_store.put_spilled(seg)
            if int(new.get("page_size", self.page_size)) != self.page_size:
                swap_store.drop(new["ref"])
                continue
            manifest[str(tid)] = new
        return manifest

    def swap_out_seq(self, store, seq: int,
                     block: Dict[str, np.ndarray]) -> Dict[str, object]:
        """Per-sequence host-swap out (ISSUE 19): publish ``block`` —
        the sequence's page payloads in table order, sliced by the
        engine's warmed extract executable — straight to a CRC-checked
        disk segment (``put_spilled``: a swap segment is cold by
        definition), then release every page the sequence holds (shared
        refs drop, exclusive pages free). Returns the snapshot the
        matching :meth:`swap_in_seq` needs; ``freed`` carries the
        exclusive-page count for the caller's eviction accounting."""
        seq = int(seq)
        pages = self.seq_pages(seq)
        if not pages:
            raise PoolAccountingError(
                f"swap_out_seq: sequence {seq} holds no pages"
            )
        ref = store.put_spilled(block)
        freed = self.free_seq(seq)
        return {
            "ref": ref,
            "pages": len(pages),
            "freed": freed,
            "page_size": self.page_size,
        }

    def swap_in_seq(self, store, snapshot: Dict[str, object],
                    seq: int) -> Tuple[List[int], Dict[str, object]]:
        """Per-sequence host-swap in: CRC-checked reload of the swap
        segment (corruption quarantines + raises ``BlockCorruptionError``
        AFTER the snapshot's ref is dropped, so the caller's counted
        fallback to recompute-replay starts clean), fresh exclusive
        pages allocated to ``seq``, segment dropped. Returns
        ``(pages, block)`` — the caller scatters the payloads into the
        pages with its warmed restore executable. The restored sequence
        owns everything exclusively (shared-prefix references are not
        re-acquired; re-sharing would need a content re-proof)."""
        from ..blockstore.store import BlockCorruptionError

        if int(snapshot["page_size"]) != self.page_size:
            raise PoolAccountingError(
                f"swap_in_seq: snapshot page_size {snapshot['page_size']}"
                f" != pool page_size {self.page_size}"
            )
        try:
            block = store.get(snapshot["ref"])
        except BlockCorruptionError:
            store.drop(snapshot["ref"])
            raise
        pages = self.alloc(int(seq), int(snapshot["pages"]))
        store.drop(snapshot["ref"])
        return pages, block

    # -- frame view ---------------------------------------------------------

    def as_frame(self):
        """The pool as a TensorFrame (one row per page, one column per
        pool array) — a materialized snapshot view for the data plane /
        debugging, not a live alias."""
        from ..frame import frame_from_arrays

        return frame_from_arrays(
            {k: np.asarray(v) for k, v in self.columns.items()},
            num_blocks=1,
        )

    def __repr__(self) -> str:  # pragma: no cover - convenience
        return (
            f"PagedKVPool(pages={self.num_pages}, "
            f"page_size={self.page_size}, free={self.num_free}, "
            f"shared={self.num_shared}, seqs={len(self._owned)})"
        )
