"""Online serving: continuous batching over the verb engine (ISSUE 9).

The first latency-shaped subsystem in a throughput-shaped codebase:
an async request front (:class:`Server` — ``submit()`` returns
futures; :func:`serve_http` is the thin HTTP adapter) that admits
single-row/small-batch requests against registered Programs, coalesces
them with a continuous batcher into the executor's power-of-two row
buckets (the SAME ladder ``compilecache.warmup`` precompiles, so every
flush is an AOT-cache hit), dispatches through the existing executor,
and scatters per-request results back with padding-row masking.

Guarantees, stated once:

* **bit-identity** — a coalesced request's rows equal its solo
  dispatch exactly (row-independent vmapped programs; padding rows are
  sliced off before scatter);
* **zero steady-state compiles** — a warmed server never hits XLA
  under any mix of admissible request sizes;
* **boundedness** — admission past the queue bound sheds with a
  counted rejection (never a hang), per-request deadlines follow
  ``RetryPolicy.deadline_s`` total-elapsed semantics, and shutdown
  drains gracefully;
* **observability** — ``tftpu_serving_*`` metrics, ``serving.flush`` /
  ``serving.request`` trace spans, and flight-recorder ``serving.*``
  records ride the standard registry/tracer/black-box surfaces.

ISSUE 11 adds the **iterative decode engine** on top
(:class:`DecodeEngine` via ``Server.register_decode``): token-level
continuous batching over a block-paged int8 KV pool
(:class:`PagedKVPool`) — sequence slots join/leave the running batch
every step, the pool preempts (evict + requeue + bit-identical resume)
when full, and the same four contracts hold per token instead of per
flush. See docs/serving.md ("Iterative decode").

ISSUE 13 scales it out: :class:`ServingFleet` runs N supervised
replica servers (heartbeats, per-replica crash restart, ONE shared
compile store so restarts warm with zero XLA compiles) behind a
:class:`Router` ingress that load-balances by queue depth, routes only
to ``state=running`` replicas, and redrives failed dispatches to
survivors under the original deadline with idempotency-key dedup —
every admitted request gets exactly one response through a ``kill -9``.
See docs/serving.md ("Scale-out").

ISSUE 20 serves the relational plane itself: ``Server.register_query``
turns a lazy map→join→aggregate pipeline over a growing scan directory
into an endpoint — fronted by a (plan-fingerprint × input-content-
digest) result cache with counted invalidation, with algebraic
aggregates maintained incrementally per arriving chunk (bit-identical
to full recompute by exact associativity; anything outside the
contract degrades to a COUNTED full recompute and a TFG114
diagnostic). See docs/serving.md ("Registered queries").
"""

from __future__ import annotations

from . import metrics  # noqa: F401  (registers tftpu_serving_* at import)
from .batcher import (  # noqa: F401
    ContinuousBatcher,
    DeadlineExceededError,
    RejectedError,
    ResultFuture,
    ServingError,
)
from .decode import DecodeConfig, DecodeEngine  # noqa: F401
from .fleet import FleetDegradedError, ServingFleet  # noqa: F401
from .http import serve_http  # noqa: F401
from .replica import serve_replica  # noqa: F401
from .router import Router, RouterConfig  # noqa: F401
from .kvpool import (  # noqa: F401
    PagedKVPool,
    PoolAccountingError,
    PoolExhaustedError,
)
from .query import (  # noqa: F401
    QueryEndpoint,
    QuerySource,
    query_cache_events,
)
from .server import (  # noqa: F401
    Endpoint,
    Server,
    ServingConfig,
    UnknownEndpointError,
)

__all__ = [
    "Server",
    "ServingConfig",
    "Endpoint",
    "ContinuousBatcher",
    "ResultFuture",
    "ServingError",
    "RejectedError",
    "DeadlineExceededError",
    "UnknownEndpointError",
    "DecodeConfig",
    "DecodeEngine",
    "PagedKVPool",
    "PoolAccountingError",
    "PoolExhaustedError",
    "QueryEndpoint",
    "QuerySource",
    "query_cache_events",
    "serve_http",
    "serve_replica",
    "Router",
    "RouterConfig",
    "ServingFleet",
    "FleetDegradedError",
    "metrics",
]
