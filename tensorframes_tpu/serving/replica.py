"""Replica-side runner for the serving fleet (ISSUE 13).

One fleet replica = one ordinary single-process
:class:`~tensorframes_tpu.serving.Server` (PR 9/11 — continuous
batcher, warmup ladder, iterative decode) wrapped with exactly the
pieces the fleet layer above needs:

* a **heartbeat publisher** into the fleet rendezvous dir
  (``TFTPU_FLEET_DIR``; the same
  :class:`~tensorframes_tpu.resilience.fleet.Heartbeater` PR 8 fleets
  use — started BEFORE warmup, so a replica compiling for seconds reads
  alive, not dead);
* a **replica card** — one atomic JSON file publishing this replica's
  HTTP address/pid/attempt, the service-discovery record the router
  scans (heartbeats say *alive*, cards say *where*);
* the **hardened HTTP sidecar** (:func:`~tensorframes_tpu.serving.serve_http`)
  whose ``/healthz`` carries the lifecycle state the router keys on and
  whose ``/admin/drain`` is the rolling-restart hook;
* a supervised **main loop** carrying the ``serving.replica`` kill
  chaos site — a drill can SIGKILL any replica deterministically — and
  a SIGTERM handler that drains instead of dropping in-flight work.

The shared-store contract rides the environment: the fleet arms
``TFTPU_COMPILE_CACHE`` for every replica, so the first replica's
warmup publishes each ladder executable once and every later (or
RESTARTED) replica's warmup is pure store hits — **zero XLA compiles**,
the property the fleet asserts over this replica's healthz process
counters.

``python -m tensorframes_tpu.serving.replica_main --demo`` runs a
deterministic built-in endpoint (``score``: ``y = tanh(x @ w)`` with
seed-0 weights, identical in every replica — a redriven request gets
the same answer from any survivor), which is what the fleet tests,
bench, and chaos drill spawn.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, Optional

from ..observability import context as _context
from ..observability import flight as _flight
from ..resilience.faults import kill_point
from ..resilience.fleet import (
    Heartbeater,
    read_latest_records,
    write_json_atomic,
)
from ..utils import get_logger
from .http import serve_http
from .server import Server

logger = get_logger(__name__)

__all__ = [
    "publish_card", "read_cards", "card_addr", "serve_replica",
    "demo_server", "main",
]


# ---------------------------------------------------------------------------
# replica cards (service discovery: heartbeats say alive, cards say where)
# ---------------------------------------------------------------------------

def _card_path(directory: str, run_id: str, rank: int) -> str:
    return os.path.join(directory, f"replica_{run_id}_p{rank}.json")


def publish_card(
    directory: str,
    *,
    rank: int,
    addr: str,
    port: int,
    run_id: Optional[str] = None,
    attempt: int = 0,
) -> str:
    """Atomically publish this replica's address card into the
    rendezvous dir (tmp-write + rename, like heartbeats — a router scan
    never sees a torn card). A restarted replica overwrites its rank's
    card with the new ephemeral port."""
    run_id = run_id or _context.run_id()
    rec = {
        "run_id": run_id,
        "rank": int(rank),
        "addr": str(addr),
        "port": int(port),
        "pid": os.getpid(),
        "attempt": int(attempt),
        "ts": time.time(),
    }
    os.makedirs(directory, exist_ok=True)
    return write_json_atomic(_card_path(directory, run_id, rank), rec)


def card_addr(card: dict) -> str:
    """The ``host:port`` dial address a replica card advertises — ONE
    formatting of the card schema, shared by the router's discovery
    and the fleet's drain path."""
    return f"{card.get('addr', '127.0.0.1')}:{card['port']}"


def read_cards(
    directory: str, run_id: Optional[str] = None
) -> Dict[int, dict]:
    """Every published replica card (``{rank: record}``), filtered to
    ``run_id`` when given — the same tolerant newest-per-rank read the
    heartbeat files use (one implementation, resilience/fleet.py)."""
    pattern = (
        f"replica_{run_id}_p*.json" if run_id else "replica_*_p*.json"
    )
    return read_latest_records(
        directory, pattern, run_id, rank_field="rank"
    )


# ---------------------------------------------------------------------------
# the replica main loop
# ---------------------------------------------------------------------------

def serve_replica(
    server: Server,
    *,
    addr: str = "127.0.0.1",
    port: int = 0,
    fleet_dir: Optional[str] = None,
    rank: Optional[int] = None,
    poll_s: float = 0.05,
    http_kwargs: Optional[dict] = None,
) -> int:
    """Run ``server`` as one fleet replica until it is drained or
    terminated; returns the exit code (0 = clean). Blocks the calling
    thread — this IS the replica process's main loop.

    Order matters: the heartbeat starts **before** ``server.start()``
    (warmup can take seconds; the supervisor must read the replica as
    alive-but-starting, and the router reads ``state=starting`` from
    healthz and keeps traffic away), the card publishes **after** the
    HTTP port is bound (a card must never point at an unbound port).
    SIGTERM triggers a graceful drain (in-flight + queued work
    completes, state walks ``draining`` → ``stopped``); the loop also
    exits when an external ``POST /admin/drain`` lands — either way the
    final heartbeat is a clean ``stopped`` beat. The loop carries the
    ``serving.replica`` kill site: an armed
    :class:`~tensorframes_tpu.resilience.faults.KillRank` SIGKILLs this
    replica deterministically (the fleet-chaos drill's trigger)."""
    fleet_dir = fleet_dir or os.environ.get("TFTPU_FLEET_DIR") or None
    rank = _context.process_index() if rank is None else int(rank)
    attempt = int(os.environ.get("TFTPU_FLEET_ATTEMPT", "0") or 0)
    hb: Optional[Heartbeater] = None
    if fleet_dir:
        hb = Heartbeater(fleet_dir, rank=rank).start()
    stop_evt = threading.Event()

    def _on_term(signum, frame):  # noqa: ARG001 - signal API
        logger.info("replica %d: SIGTERM — draining", rank)
        stop_evt.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # pragma: no cover - non-main thread (tests)
        pass
    httpd = None
    rc = 0
    try:
        server.start()  # warm (store hits on a warmed fleet) + open
        httpd = serve_http(server, port=port, addr=addr,
                           **(http_kwargs or {}))
        bound_port = int(httpd.server_address[1])
        if fleet_dir:
            publish_card(
                fleet_dir, rank=rank, addr=addr, port=bound_port,
                attempt=attempt,
            )
        _flight.record(
            "serving.replica_up", rank=rank, port=bound_port,
            attempt=attempt, endpoints=server.endpoints(),
        )
        logger.info(
            "replica %d up on %s:%d (attempt %d)", rank, addr,
            bound_port, attempt,
        )
        while True:
            # the kill chaos site: armed KillRank → SIGKILL self, the
            # deterministic stand-in for an OOM-killed/preempted replica
            kill_point("serving.replica")
            if stop_evt.is_set():
                server.stop(drain=True)
                break
            if server.state == "stopped":
                break  # drained externally (POST /admin/drain)
            time.sleep(poll_s)
    except Exception as e:  # pragma: no cover - crash path
        logger.error("replica %d failed: %s", rank, e)
        _flight.record(
            "serving.replica_error", rank=rank,
            error=type(e).__name__, message=str(e),
        )
        rc = 1
    finally:
        if httpd is not None:
            httpd.shutdown()
        if hb is not None:
            # graceful final beat IFF we exited cleanly: a crash path
            # must read as dead, not departed
            hb.stop(graceful=(rc == 0))
        _flight.record("serving.replica_down", rank=rank, rc=rc)
    return rc


# ---------------------------------------------------------------------------
# the demo replica (what fleet tests / bench / drills spawn)
# ---------------------------------------------------------------------------

def demo_server(width: int = 8, max_batch_rows: int = 8,
                max_latency_s: float = 0.002,
                max_queue_rows: int = 1024) -> Server:
    """A deterministic one-endpoint server: ``score`` computes
    ``y = tanh(x @ w)`` with seed-0 weights — every replica holds the
    SAME weights, so a redriven request is answered identically by any
    survivor (the property the redrive tests pin)."""
    import jax.numpy as jnp
    import numpy as np

    import tensorframes_tpu as tfs
    from .server import ServingConfig

    rng = np.random.default_rng(0)
    w = (rng.standard_normal((width, width)) / np.sqrt(width)).astype(
        np.float32
    )
    schema = tfs.Schema([
        tfs.ColumnInfo(
            "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, width))
        )
    ])
    holder = type("S", (), {"schema": schema})()
    prog = tfs.compile_program(
        lambda x: {"y": jnp.tanh(x @ w)}, holder, block=False
    )
    srv = Server(ServingConfig(
        max_batch_rows=max_batch_rows, max_latency_s=max_latency_s,
        max_queue_rows=max_queue_rows,
    ))
    srv.register("score", prog)
    return srv


def main(argv=None) -> int:
    """``python -m tensorframes_tpu.serving.replica_main [--demo]`` —
    run the demo replica under the current fleet environment (the entry
    lives in ``replica_main.py``, which the package never imports, so
    ``-m`` does not double-execute this module). Chaos arming via env
    (for drills — deterministic, no code in the victim):
    ``TFTPU_SERVING_CHAOS_KILL_AFTER=<n>`` SIGKILLs this replica after
    *n* main-loop beats, on attempt 0 only (the restarted incarnation
    must survive), when this rank matches
    ``TFTPU_SERVING_CHAOS_KILL_RANK`` (default 1)."""
    import argparse
    import contextlib

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--demo", action="store_true",
                        help="serve the built-in deterministic endpoint")
    parser.add_argument("--width", type=int, default=8)
    parser.add_argument("--max-batch-rows", type=int, default=8)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--addr", default="127.0.0.1")
    args = parser.parse_args(argv)
    if not args.demo:
        parser.error("only --demo is runnable standalone; real apps "
                     "call serve_replica(server) from their own worker")
    stack = contextlib.ExitStack()
    kill_after = int(os.environ.get("TFTPU_SERVING_CHAOS_KILL_AFTER", 0))
    kill_rank = int(os.environ.get("TFTPU_SERVING_CHAOS_KILL_RANK", 1))
    attempt = int(os.environ.get("TFTPU_FLEET_ATTEMPT", "0") or 0)
    if (kill_after > 0 and attempt == 0
            and _context.process_index() == kill_rank):
        from ..resilience import faults

        stack.enter_context(faults.inject(
            "serving.replica", faults.KillRank, after=kill_after,
            max_times=1,
        ))
    with stack:
        srv = demo_server(
            width=args.width, max_batch_rows=args.max_batch_rows,
        )
        return serve_replica(srv, addr=args.addr, port=args.port)
