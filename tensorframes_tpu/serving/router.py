"""Fleet router: one fault-tolerant ingress over N replica servers.

The reference's cluster manager placed one user program over a fleet of
executors; this module is the serving-shaped analogue (ISSUE 13,
ROADMAP #2): a single HTTP ingress that owns **placement** (which live
replica gets the next request) and **failure** (what happens to a
request whose replica died mid-flight), while each replica below it
keeps its compiled fast path — warmed bucket ladder, continuous
batcher, zero steady-state compiles — completely intact (the Flare
trade, arxiv 1703.08219: the cluster layer must not cost the per-node
compiled path anything).

Three contracts, stated once:

* **placement** — dispatch goes to the live ``state=running`` replica
  with the smallest load (scraped ``tftpu_serving_queue_depth`` rows
  from each replica's healthz, plus this router's own in-flight count
  per replica, which covers the scrape staleness window). A replica
  that is ``starting``, ``draining``, ``stopped``, heartbeat-stale, or
  scrape-dead is **never** picked — readiness and heartbeats are one
  verdict, so no request is routed to a dead or draining replica.
* **redrive** — a dispatch whose replica fails mid-request (connection
  refused/reset/dropped, or a ``closed`` 503 from a draining race) is
  re-dispatched to a surviving replica under the request's ORIGINAL
  deadline, carrying the same **idempotency key**: a replica that
  already admitted the first attempt joins it to the original future
  (``Server.submit`` dedup) instead of executing twice. Every admitted
  ingress request gets exactly one response — success or a counted
  error, never silence.
* **boundedness** — no live replica → counted 503 ``no_replica``; the
  deadline lapsing mid-redrive → counted 504 ``deadline``; a request
  without a deadline gets a bounded redrive budget instead of an
  unbounded retry loop.

The ``router.dispatch`` fault site sits on the dispatch path: an
injected ``Delay`` stalls a proxied dispatch (deadline-expiry chaos),
any other injected error fails the attempt exactly like a replica
connection failure — which makes the redrive machinery deterministically
drillable without killing anything.

Observability: ``tftpu_router_*`` metrics (serving/metrics.py) and the
flight-recorder ``router.*`` family (``router.start`` / ``redrive`` /
``replica_dead`` / ``replica_ready`` / ``no_replica`` / ``stop``).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from ..config import get_config
from ..observability import context as _context
from ..observability import events as _events
from ..observability import flight as _flight
from ..resilience.faults import delay_point
from ..utils import get_logger
from . import metrics as m
from .http import (
    DEFAULT_MAX_BODY_BYTES,
    DEFAULT_MAX_CONNECTIONS,
    DEFAULT_READ_TIMEOUT_S,
    make_hardened_http_server,
    parse_json_object,
    read_bounded_body,
    reply_json,
)
from .replica import card_addr, read_cards

logger = get_logger(__name__)

__all__ = ["RouterConfig", "ReplicaHandle", "Router", "http_json"]


@dataclasses.dataclass
class RouterConfig:
    """Router knobs. ``poll_s`` — healthz scrape + heartbeat/card scan
    cadence (the staleness bound on queue depths and readiness).
    ``scrape_timeout_s`` — per-scrape HTTP timeout. ``scrape_fails_dead``
    — consecutive scrape failures before a replica is marked dead
    (heartbeat staleness and a fleet ``mark_dead`` act immediately).
    ``default_deadline_s`` — applied to ingress requests that carry
    none (``None`` = no deadline; such requests get
    ``redrive_budget`` dispatch attempts instead of a clock).
    ``redrive_wait_s`` — pause before re-picking when every known
    replica is excluded (a restarting replica may rejoin)."""

    poll_s: float = 0.1
    scrape_timeout_s: float = 2.0
    scrape_fails_dead: int = 3
    heartbeat_timeout_s: Optional[float] = None
    default_deadline_s: Optional[float] = None
    redrive_budget: int = 4
    redrive_wait_s: float = 0.05
    no_replica_wait_s: float = 2.0
    #: HTTP timeout for DEADLINE-LESS dispatches (a deadline-carrying
    #: request is bounded by its own remaining budget instead). Large
    #: on purpose: a long-but-legitimate batch must not be aborted and
    #: re-executed; a wedged replica is caught by heartbeats/scrapes,
    #: not this bound.
    dispatch_timeout_s: float = 300.0
    max_body_bytes: int = DEFAULT_MAX_BODY_BYTES
    read_timeout_s: Optional[float] = DEFAULT_READ_TIMEOUT_S
    max_connections: int = DEFAULT_MAX_CONNECTIONS


class ReplicaHandle:
    """The router's view of one replica: where it is, whether it is
    routable, and how loaded it looks."""

    def __init__(self, rank: int, addr: str):
        self.rank = int(rank)
        self.addr = str(addr)  # "host:port"
        self.state = "unknown"  # scraped lifecycle state, or unknown/dead
        self.queued_rows = 0
        self.inflight = 0  # this router's not-yet-answered dispatches
        self.scrape_fails = 0
        self.scraping = False  # a scrape of this handle is in flight
        #: has this replica EVER scraped as running? Gates the
        #: scrape-failure dead verdict: a freshly-spawned replica is
        #: connection-refused for seconds while it warms (not dead),
        #: but one that WAS serving and stops answering is.
        self.ever_running = False
        self.beat_age_s: Optional[float] = None
        self.pid: Optional[int] = None
        self.attempt = 0
        self.dead_reason: Optional[str] = None
        self.process: Dict[str, int] = {}  # compile counters, last scrape

    @property
    def routable(self) -> bool:
        return self.state == "running"

    def load(self) -> int:
        return self.queued_rows + self.inflight

    def snapshot(self) -> dict:
        return {
            "rank": self.rank, "addr": self.addr, "state": self.state,
            "queued_rows": self.queued_rows, "inflight": self.inflight,
            "attempt": self.attempt, "pid": self.pid,
            "beat_age_s": self.beat_age_s,
            "dead_reason": self.dead_reason,
            "ever_running": self.ever_running,
            "process": dict(self.process),
        }


class Router:
    """The ingress: keep a live replica registry, pick by queue depth,
    redrive on failure. Discovery modes compose: a static ``replicas``
    list/dict of ``host:port`` addresses, and/or a fleet rendezvous
    ``fleet_dir`` whose replica cards + heartbeats are scanned every
    poll (the :class:`~tensorframes_tpu.serving.ServingFleet` mode —
    restarted replicas republish their card and rejoin automatically).
    """

    def __init__(self, replicas=None, *, fleet_dir: Optional[str] = None,
                 run_id: Optional[str] = None,
                 config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig()
        self.fleet_dir = fleet_dir
        self.run_id = run_id or (_context.run_id() if fleet_dir else None)
        self._lock = threading.Lock()
        self._replicas: Dict[int, ReplicaHandle] = {}
        self._counters = {
            "requests": 0, "redrives": 0,
            "rejected": {r: 0 for r in m.ROUTER_REJECT_REASONS},
        }
        self._seq = itertools.count()
        self._poller: Optional[threading.Thread] = None
        self._scrape_pool = None  # lazy ThreadPoolExecutor
        self._stop = threading.Event()
        self._httpd = None
        if replicas is not None:
            pairs = (
                replicas.items() if isinstance(replicas, dict)
                else enumerate(replicas)
            )
            for rank, addr in pairs:
                self.set_replica(int(rank), str(addr))

    # -- registry -----------------------------------------------------------

    def set_replica(self, rank: int, addr: str, *,
                    pid: Optional[int] = None, attempt: int = 0) -> None:
        """Register (or re-register after a restart) a replica. State
        starts ``unknown`` — it becomes routable only once a scrape
        reads ``running`` from its healthz."""
        with self._lock:
            h = self._replicas.get(rank)
            if h is None or h.addr != addr or h.attempt != attempt:
                h = ReplicaHandle(rank, addr)
                h.pid = pid
                h.attempt = int(attempt)
                self._replicas[rank] = h

    def mark_dead(self, rank: int, reason: str = "reaped") -> None:
        """Immediate death verdict (the fleet supervisor reaped the
        process): stop routing to it NOW, without waiting for a scrape
        or heartbeat timeout. In-flight dispatches to it fail on their
        sockets and redrive."""
        with self._lock:
            h = self._replicas.get(rank)
            if h is None or h.state == "dead":
                return
            h.state = "dead"
            h.dead_reason = reason
        m.ROUTER_REPLICA_DEAD.inc()
        _flight.record("router.replica_dead", rank=rank, reason=reason)
        logger.warning("router: replica %d dead (%s)", rank, reason)

    def replicas(self) -> Dict[int, dict]:
        with self._lock:
            return {r: h.snapshot() for r, h in self._replicas.items()}

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for h in self._replicas.values() if h.routable)

    # -- polling ------------------------------------------------------------

    def start(self) -> "Router":
        if self._poller is None:
            self._stop.clear()
            self._poll_once()  # ready replicas visible before first pick
            self._poller = threading.Thread(
                target=self._poll_loop, daemon=True, name="tfs-router-poll"
            )
            self._poller.start()
            _flight.record(
                "router.start", replicas=sorted(self._replicas),
                fleet_dir=self.fleet_dir,
            )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=self.config.poll_s * 4 + 2.0)
            self._poller = None
        if self._scrape_pool is not None:
            self._scrape_pool.shutdown(wait=False)
            self._scrape_pool = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        _flight.record("router.stop")

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.config.poll_s):
            try:
                self._poll_once()
            except Exception as e:  # pragma: no cover - must keep polling
                logger.debug("router poll failed: %s", e)

    def _poll_once(self) -> None:
        from ..resilience.fleet import read_heartbeats

        if self.fleet_dir:
            for rank, card in read_cards(self.fleet_dir, self.run_id).items():
                self.set_replica(
                    rank, card_addr(card),
                    pid=card.get("pid"), attempt=card.get("attempt", 0),
                )
            timeout = (
                self.config.heartbeat_timeout_s
                if self.config.heartbeat_timeout_s is not None
                else get_config().heartbeat_timeout_s
            )
            try:
                beats = read_heartbeats(self.fleet_dir, self.run_id)
            except OSError:  # pragma: no cover - transient fs wobble
                beats = {}
            now = time.time()
            with self._lock:
                handles = list(self._replicas.values())
            for h in handles:
                rec = beats.get(h.rank)
                if rec is None:
                    continue
                age = max(0.0, now - float(rec.get("ts", now)))
                with self._lock:
                    h.beat_age_s = round(age, 3)
                if rec.get("stopped"):
                    with self._lock:
                        if h.state not in ("dead", "stopped"):
                            h.state = "stopped"
                elif age > timeout and h.state != "dead":
                    self.mark_dead(
                        h.rank,
                        f"heartbeat stale {age:.2f}s (timeout {timeout:g}s)",
                    )
        with self._lock:
            # DEAD handles are scraped too: dead is a routing verdict,
            # not a tombstone — an alive-but-stalled replica whose
            # healthz recovers (transient GIL stall, connection flood)
            # must resurrect instead of being blacklisted forever (a
            # truly reaped process just keeps refusing the connection,
            # and its restart arrives as a NEW card/attempt anyway).
            # Skip handles whose previous scrape is STILL in flight (a
            # wedged replica pinning a pool thread): overlapping
            # scrapes of one handle could interleave verdicts.
            handles = [
                h for h in self._replicas.values() if not h.scraping
            ]
            for h in handles:
                h.scraping = True
        if len(handles) == 1:
            self._scrape(handles[0])
        elif handles:
            # scrape CONCURRENTLY: one wedged replica (accepts, never
            # answers — a scrape_timeout_s stall) must not stretch the
            # poll cadence by 2s per wedged peer, delaying readiness
            # and death detection for the whole fleet
            import concurrent.futures as _cf

            pool = self._scrape_pool
            if pool is None:
                pool = self._scrape_pool = _cf.ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="tfs-router-scrape"
                )
            futs = [pool.submit(self._scrape, h) for h in handles]
            _cf.wait(futs, timeout=self.config.scrape_timeout_s + 1.0)
        m.ROUTER_REPLICAS_LIVE.set(self.live_count())

    def _scrape(self, h: ReplicaHandle) -> None:
        """One healthz read: lifecycle state + queue depth + process
        compile counters. Scrape failures accumulate toward a dead
        verdict (connection refused on a freshly-spawned replica is
        normal — the fails threshold and heartbeats arbitrate). The
        caller marked ``h.scraping``; cleared here in ``finally``."""
        try:
            self._scrape_inner(h)
        finally:
            with self._lock:
                h.scraping = False

    def _scrape_inner(self, h: ReplicaHandle) -> None:
        status, body = http_json(
            h.addr, "GET", "/healthz", None, self.config.scrape_timeout_s
        )
        became_ready = False
        with self._lock:
            if status != 200 or not isinstance(body, dict):
                h.scrape_fails += 1
                if h.state == "running":
                    h.state = "unknown"  # suspect: stop routing NOW
                # the dead verdict needs BOTH repeated failures and a
                # replica that has ever served: a freshly-spawned one
                # is connection-refused for seconds while warming (not
                # dead — it stays un-routable until it answers), but
                # one that WAS running and keeps failing scrapes is
                dead = (
                    h.ever_running
                    and h.scrape_fails >= self.config.scrape_fails_dead
                )
            else:
                was = h.state
                h.scrape_fails = 0
                h.state = str(body.get("state", "unknown"))
                if h.state == "running":
                    h.ever_running = True
                    h.dead_reason = None  # resurrection: verdict undone
                h.queued_rows = int(
                    sum((body.get("queued_rows") or {}).values())
                )
                proc = body.get("process")
                if isinstance(proc, dict):
                    h.process = {k: int(v) for k, v in proc.items()}
                became_ready = was != "running" and h.state == "running"
                dead = False
        if status == 200 and became_ready:
            _flight.record(
                "router.replica_ready", rank=h.rank, addr=h.addr,
                attempt=h.attempt, process=dict(h.process),
            )
            logger.info("router: replica %d ready at %s", h.rank, h.addr)
        if dead:
            self.mark_dead(
                h.rank,
                f"healthz unreachable x{h.scrape_fails}",
            )

    # -- dispatch -----------------------------------------------------------

    def _pick(self, excluded) -> Optional[ReplicaHandle]:
        with self._lock:
            live = [
                h for h in self._replicas.values()
                if h.routable and h.rank not in excluded
            ]
            if not live:
                return None
            h = min(live, key=lambda h: (h.load(), h.rank))
            h.inflight += 1
            return h

    def _release(self, h: ReplicaHandle) -> None:
        with self._lock:
            h.inflight = max(0, h.inflight - 1)

    def dispatch(self, endpoint: str, payload: dict,
                 deadline_s: Optional[float] = None) -> Tuple[int, dict]:
        """Route one ingress request; returns ``(status, body)`` to
        relay. ``payload`` is the replica-API body (``inputs`` etc.);
        the router stamps an ``idempotency_key`` (preserving a
        client-provided one) and rewrites ``deadline_s`` to the
        REMAINING budget on every attempt, so a redrive runs under the
        original deadline, not a fresh one."""
        t0 = time.perf_counter()
        m.ROUTER_REQUESTS.inc()
        with self._lock:
            self._counters["requests"] += 1
            seq = next(self._seq)
        key = payload.get("idempotency_key") or (
            f"rt-{self.run_id or _context.run_id()}-{os.getpid()}-{seq}"
        )
        payload = dict(payload)
        payload["idempotency_key"] = key
        # cross-hop trace context (ISSUE 17): the request id IS the
        # idempotency key — stable across a redrive, so the merged
        # timeline shows one id from ingress through whichever replica
        # finally served it
        trace_val = _context.trace_header_value(key)
        m.REQUEST_TRACE.inc()
        if deadline_s is None:
            deadline_s = payload.get("deadline_s")
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is not None:
            # validated HERE, not trusted from the ingress body: a
            # malformed deadline must be a clean 400, never an uncaught
            # handler-thread error that drops the connection silently
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                return 400, {
                    "error": (
                        f"deadline_s must be a number, got "
                        f"{payload.get('deadline_s')!r}"
                    ),
                }
            if deadline_s <= 0:
                return 400, {
                    "error": (
                        f"deadline_s must be > 0 (got {deadline_s}) — "
                        "the RetryPolicy.deadline_s contract"
                    ),
                }
        abs_deadline = (
            None if deadline_s is None else t0 + deadline_s
        )
        excluded: set = set()
        attempts = 0
        no_replica_since: Optional[float] = None
        try:
            while True:
                now = time.perf_counter()
                if abs_deadline is not None and now >= abs_deadline:
                    return self._reject(
                        "deadline", endpoint,
                        f"deadline of {deadline_s:g}s lapsed after "
                        f"{attempts} dispatch attempt(s)",
                    )
                if abs_deadline is None and attempts >= \
                        self.config.redrive_budget:
                    return self._reject(
                        "deadline", endpoint,
                        f"redrive budget ({self.config.redrive_budget} "
                        "attempts) exhausted for a deadline-less request",
                    )
                rep = self._pick(excluded)
                if rep is None and excluded:
                    # every known replica tried: start a fresh round —
                    # a restarted replica may have rejoined by now
                    excluded.clear()
                    time.sleep(self.config.redrive_wait_s)
                    continue
                if rep is None:
                    if no_replica_since is None:
                        no_replica_since = now
                    waited = now - no_replica_since
                    bound = self.config.no_replica_wait_s
                    if abs_deadline is not None:
                        bound = min(bound, max(0.0, abs_deadline - now))
                    if waited >= bound:
                        return self._reject(
                            "no_replica", endpoint,
                            "no live replica (all dead, draining, or "
                            "still starting)",
                        )
                    time.sleep(
                        min(self.config.redrive_wait_s, 0.05)
                    )
                    continue
                no_replica_since = None
                attempts += 1
                t_att = time.perf_counter()
                lapsed = False
                try:
                    delay_point("router.dispatch")
                    # remaining budget computed AFTER the fault site: a
                    # stalled dispatch (Delay chaos, scheduler pause)
                    # must shrink the replica-side deadline, not reset it
                    if abs_deadline is not None:
                        remaining = abs_deadline - time.perf_counter()
                        if remaining <= 0:
                            lapsed = True
                        else:
                            payload["deadline_s"] = remaining
                    if not lapsed:
                        timeout = self.config.dispatch_timeout_s
                        if abs_deadline is not None:
                            timeout = remaining + 1.0
                        status, body = http_json(
                            rep.addr, "POST", f"/v1/{endpoint}",
                            payload, timeout,
                            headers={_context.TRACE_HEADER: trace_val},
                        )
                except Exception as e:
                    # an injected router.dispatch error counts as a
                    # failed attempt, exactly like a dead socket.
                    # Exception, NOT BaseException: a KeyboardInterrupt
                    # mid-dispatch must interrupt the retry loop, not
                    # be counted as a replica failure and redriven
                    status, body = None, {"error": str(e)}
                finally:
                    self._release(rep)
                    m.ROUTER_DISPATCH_SECONDS.observe(
                        time.perf_counter() - t_att
                    )
                if lapsed:
                    return self._reject(
                        "deadline", endpoint,
                        f"deadline of {deadline_s:g}s lapsed during "
                        f"dispatch attempt {attempts}",
                    )
                if status is None:
                    # network-level failure: the replica died (or the
                    # connection did) mid-request — redrive to a
                    # survivor under the same key + remaining deadline
                    excluded.add(rep.rank)
                    with self._lock:
                        if rep.state == "running":
                            rep.state = "unknown"  # suspect until rescape
                        self._counters["redrives"] += 1
                    m.ROUTER_REDRIVES.inc()
                    _flight.record(
                        "router.redrive", endpoint=endpoint,
                        from_rank=rep.rank, key=key, attempt=attempts,
                        error=str(body.get("error"))[:200],
                    )
                    logger.warning(
                        "router: redriving %s after replica %d failed "
                        "(%s)", endpoint, rep.rank, body.get("error"),
                    )
                    continue
                if status == 503 or status == 429:
                    # closed (draining race) or backpressure: another
                    # replica may take it; relay only when there is no
                    # alternative left this round
                    with self._lock:
                        alternatives = any(
                            h.routable and h.rank not in excluded
                            and h.rank != rep.rank
                            for h in self._replicas.values()
                        )
                    if alternatives:
                        excluded.add(rep.rank)
                        with self._lock:
                            self._counters["redrives"] += 1
                        m.ROUTER_REDRIVES.inc()
                        _flight.record(
                            "router.redrive", endpoint=endpoint,
                            from_rank=rep.rank, key=key,
                            attempt=attempts, status=status,
                        )
                        continue
                if isinstance(body, dict):
                    body.setdefault("replica", rep.rank)
                return status, body
        finally:
            dur = time.perf_counter() - t0
            m.ROUTER_REQUEST_LATENCY.observe(dur)
            if _events.TRACER.enabled:
                # the ingress half of the cross-process request span:
                # merge joins it to the replica's serving.* spans via
                # the shared request_id arg
                _events.TRACER.emit_complete(
                    "router.request", t0, dur,
                    args={"request_id": key, "endpoint": endpoint,
                          "attempts": attempts},
                    cat="serving",
                )

    def _reject(self, reason: str, endpoint: str,
                message: str) -> Tuple[int, dict]:
        m.router_rejected(reason).inc()
        with self._lock:
            self._counters["rejected"][reason] += 1
        _flight.record(
            "router.no_replica" if reason == "no_replica"
            else "router.deadline",
            endpoint=endpoint, message=message,
        )
        code = 503 if reason == "no_replica" else 504
        return code, {"error": message, "reason": reason}

    # -- introspection ------------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                "requests": self._counters["requests"],
                "redrives": self._counters["redrives"],
                "rejected": dict(self._counters["rejected"]),
            }

    def status(self) -> dict:
        return {
            "role": "router",
            "replicas": self.replicas(),
            "live": self.live_count(),
            **self.counters(),
        }

    # -- the ingress HTTP front ---------------------------------------------

    def serve(self, port: int = 0, addr: str = "127.0.0.1"):
        """Expose the router over HTTP (the single fleet ingress):
        ``POST /v1/<endpoint>`` proxied through :meth:`dispatch`,
        ``GET /healthz`` → :meth:`status`. Same hardening bounds as the
        replica sidecar (413 / read timeout / connection cap). Returns
        the bound ``ThreadingHTTPServer``."""
        from http.server import BaseHTTPRequestHandler

        router = self
        cfg = self.config

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            timeout = cfg.read_timeout_s

            def _reply(self, code: int, payload: dict) -> None:
                reply_json(self, code, payload)

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?")[0] in ("/", "/healthz"):
                    self._reply(200, router.status())
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802 - http.server API
                path = self.path.split("?")[0]
                if not path.startswith("/v1/"):
                    self._reply(404, {"error": "not found"})
                    return
                endpoint = path[len("/v1/"):]
                raw = read_bounded_body(
                    self, cfg.max_body_bytes, cfg.read_timeout_s
                )
                if raw is None:
                    return
                req = parse_json_object(self, raw)
                if req is None:
                    return
                try:
                    status, body = router.dispatch(endpoint, req)
                except Exception as e:
                    # the exactly-one-response contract: an unexpected
                    # dispatch error must become a 500, never a dropped
                    # connection from a dead handler thread
                    logger.warning("router ingress error: %s", e)
                    status, body = 500, {
                        "error": f"{type(e).__name__}: {e}"
                    }
                self._reply(status, body)

            def log_message(self, *args):  # noqa: D102
                pass

        httpd = make_hardened_http_server(
            (addr, port), Handler, cfg.max_connections
        )
        t = threading.Thread(
            target=httpd.serve_forever, daemon=True,
            name="tfs-router-http",
        )
        t.start()
        self._httpd = httpd
        return httpd


def http_json(addr: str, method: str, path: str,
               payload: Optional[dict], timeout: float,
               headers: Optional[Dict[str, str]] = None,
               ) -> Tuple[Optional[int], dict]:
    """One bounded HTTP exchange with a replica. Returns
    ``(status, parsed body)``; ``(None, {"error": ...})`` on any
    network-level failure (refused, reset, timeout, torn reply) — the
    caller's signal to redrive. ``headers`` adds/overrides request
    headers (the router's trace-context stamp)."""
    import http.client

    host, _, port = addr.rpartition(":")
    conn = http.client.HTTPConnection(
        host or "127.0.0.1", int(port), timeout=timeout
    )
    try:
        body = None
        headers = dict(headers or {})
        if payload is not None:
            body = json.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            parsed = json.loads(raw) if raw else {}
            if not isinstance(parsed, dict):
                parsed = {"body": parsed}
        except ValueError:
            parsed = {"error": f"unparseable reply ({len(raw)} bytes)"}
        return resp.status, parsed
    except (OSError, http.client.HTTPException) as e:
        return None, {"error": f"{type(e).__name__}: {e}"}
    finally:
        conn.close()
