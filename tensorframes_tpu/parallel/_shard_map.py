"""Version-tolerant ``shard_map`` entry point (single copy for the whole
package): jax >= 0.8 exposes ``jax.shard_map`` with ``check_vma``; older
releases have ``jax.experimental.shard_map.shard_map`` with ``check_rep``.
"""

from __future__ import annotations


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    try:
        from jax import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check
        )
    except ImportError:  # pragma: no cover — old jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
        )
