"""Version-tolerant ``shard_map`` / mesh-context entry points (single
copy for the whole package): jax >= 0.8 exposes ``jax.shard_map`` with
``check_vma``; older releases have
``jax.experimental.shard_map.shard_map`` with ``check_rep``. Likewise
``jax.sharding.use_mesh`` supersedes entering the ``Mesh`` object as a
context manager.
"""

from __future__ import annotations

import contextlib


def mesh_context(mesh):
    """Context manager installing ``mesh`` as the ambient mesh for
    tracing (axis names resolvable by ``with_sharding_constraint``/
    collectives) — ``jax.sharding.use_mesh`` on new jax, the legacy
    ``with mesh:`` entry elsewhere, and a no-op for ``mesh=None``. Used
    by the sharded TFG108 probe, which must re-trace a program exactly
    as the executor traced it, without touching device data."""
    if mesh is None:
        return contextlib.nullcontext()
    import jax

    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        try:
            return use_mesh(mesh)
        except Exception:  # pragma: no cover - jax internals moved
            pass
    return mesh


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    try:
        from jax import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check
        )
    except ImportError:  # pragma: no cover — old jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check
        )
