"""Multi-host (multi-process) initialization.

The reference ships work to executors over Spark's cluster runtime; the
TPU-native equivalent is JAX's single-controller multi-process model: one
process per TPU host, all running the same program, glued by
``jax.distributed.initialize`` (DCN for control, ICI/DCN for collectives).
One Spark-executor-per-host maps to one-process-per-host (the BASELINE
north star's deployment shape).

On a single host this module is a no-op; every entry point is safe to call
unconditionally.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..observability import context as obs_context
from ..resilience import fleet as _fleet
from ..resilience.faults import fault_point
from ..resilience.retry import RetryPolicy, retry_call
from ..utils import get_logger

logger = get_logger(__name__)

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    retry: Optional[RetryPolicy] = None,
) -> None:
    """Initialize multi-process JAX if configured (env vars or args).

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``); if neither args nor env are
    present this is a single-process no-op.

    ``retry`` (a :class:`~tensorframes_tpu.resilience.RetryPolicy`)
    re-attempts the coordinator handshake: in a preemption-restart fleet
    the workers race the coordinator back up, and the losers must back
    off and redial instead of dying at t=0. ``retry.deadline_s`` caps
    the **total** redial budget (a flaky coordinator must not stretch
    init unboundedly), and ``configure(dispatch_deadline_s=)``
    additionally bounds each handshake attempt via the hung-dispatch
    watchdog.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        logger.debug("init_distributed: single-process mode (no coordinator)")
        return
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0")
    )

    def _handshake_live() -> bool:
        """True when a previously-abandoned (deadline-expired) attempt
        finished the handshake on its daemon thread — the runtime is
        connected even though OUR call timed out."""
        try:
            from jax._src import distributed as _jax_distributed

            return _jax_distributed.global_state.client is not None
        except Exception:  # pragma: no cover - jax internals moved
            return False

    def connect() -> None:
        fault_point("distributed.init")
        if _handshake_live():
            logger.info(
                "init_distributed: an abandoned attempt completed the "
                "handshake; redial skipped"
            )
            return
        # the handshake is the first place a dead peer wedges a fleet:
        # under a dispatch deadline it aborts with a postmortem naming
        # the unresponsive ranks instead of blocking forever (the retry
        # policy then owns whether to redial — which is why this call
        # must NOT write the coordinated-abort signal: an abort record
        # outliving a successful redial would kill every rank the
        # moment it enrolled)
        try:
            _fleet.run_with_deadline(
                lambda: jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    local_device_ids=local_device_ids,
                ),
                describe="distributed.init",
                signal=False,
            )
        except RuntimeError:
            # the abandoned attempt can win the race BETWEEN the probe
            # above and our dial ("already initialized") — that is a
            # success, not a failure (and RuntimeError is deliberately
            # non-retryable, so without this the redial would fail a
            # fleet that is in fact fully connected)
            if _handshake_live():
                return
            raise

    retry_call(connect, policy=retry, describe="distributed.init")
    _initialized = True
    # stamp this process's telemetry identity: every trace shard,
    # metrics row, step-log line and flight record written after this
    # point carries the rank, which is what makes the fleet's artifacts
    # mergeable (observability/context.py)
    obs_context.bind(
        process_index=jax.process_index(), num_processes=num_processes
    )
    logger.info(
        "init_distributed: process %d/%d via %s",
        process_id,
        num_processes,
        coordinator_address,
    )


def fleet_barrier(name: str = "sync", timeout: Optional[float] = None) -> None:
    """Host-side fleet barrier with a deadline: every rank of the
    supervised fleet (``TFTPU_FLEET_DIR``) marks its arrival and waits
    for all peers — a missing rank raises
    :class:`~tensorframes_tpu.resilience.fleet.HungDispatchError`
    **naming the missing ranks** (after a flight-recorder postmortem and
    the coordinated-abort signal) instead of wedging the collective
    forever. A no-op on single-process / un-enrolled runs, so it is safe
    to call unconditionally at lockstep points (run start, checkpoint
    epochs). ``timeout`` overrides the dispatch-deadline default."""
    _fleet.barrier(name, deadline=timeout)


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


# lint: guarded (single-tuple read/replace is atomic under the GIL;
# worst case two threads compute the same value once)
_TOPOLOGY_MEMO = None


def process_topology() -> dict:
    """Process-index-INDEPENDENT identity of the fleet's device
    topology, for compile-cache keys (``compilecache/fingerprint.py``):
    every rank of an SPMD fleet computes the same value, so executables
    published by one rank are looked up by all — while a resized fleet
    (2 processes → 4) or a reshaped slice keys differently and misses
    cleanly instead of loading an executable compiled for the wrong
    collective schedule.

    Covers: process count, and per GLOBAL device its id, platform,
    device kind, and owning process index (the device→process map is
    what XLA's cross-host collectives are scheduled against; it is the
    same list on every rank — ``jax.devices()`` enumerates globally).

    Memoized: the device set is fixed for a backend's lifetime, and this
    runs on every fingerprint (every new feed-shape key, twice per
    TFG108 probe) — an O(n_devices) walk per call on a large fleet. The
    only in-process transition is pre- vs post-``init_distributed``,
    which changes the (process, device) counts the memo is keyed on.
    Callers must treat the returned dict as immutable."""
    global _TOPOLOGY_MEMO
    key = (int(jax.process_count()), int(jax.device_count()))
    memo = _TOPOLOGY_MEMO
    if memo is not None and memo[0] == key:
        return memo[1]
    devices = []
    for d in jax.devices():
        devices.append([
            int(d.id),
            str(getattr(d, "platform", "?")),
            str(getattr(d, "device_kind", "?")),
            int(getattr(d, "process_index", 0)),
        ])
    out = {"n_processes": key[0], "devices": devices}
    _TOPOLOGY_MEMO = (key, out)
    return out


def frame_from_process_local(data, mesh=None, axis: Optional[str] = None):
    """Build a GLOBAL sharded frame from each process's local rows.

    ≙ a Spark DataFrame whose partitions live on different executors: every
    process passes its own ``{column: local_array}`` (equal schemas; row
    counts may differ only as sharding allows) and receives a frame whose
    device columns are global ``jax.Array``s spanning all hosts
    (``jax.make_array_from_process_local_data``). Verbs on the result run
    SPMD across processes — reductions cross host boundaries through the
    compiler's collectives (ICI within a slice, DCN across slices), not a
    driver round-trip. All processes must call every verb in lockstep
    (single-controller SPMD), the multi-host contract jax programs share.
    """
    import numpy as np

    from .. import dtypes as dt
    from ..config import get_config
    from ..frame import TensorFrame
    from ..schema import ColumnInfo, Schema
    from ..shape import Shape, Unknown
    from .mesh import batch_sharding, make_mesh

    mesh = mesh or make_mesh()
    axis = axis or get_config().batch_axis
    block = {}
    host_block = {}
    infos = []
    host_infos = []
    n_local = None
    for name, v in data.items():
        arr_np = np.asarray(v)
        dtype = dt.from_numpy(arr_np.dtype)
        if n_local is None:
            n_local = len(v)
        elif len(v) != n_local:
            raise ValueError(
                f"Column {name!r} has {len(v)} rows, expected {n_local}"
            )
        if not dtype.device:
            # host-only columns (strings, …) stay PROCESS-LOCAL: each
            # process sees only its own rows. Usable as aggregate keys
            # (the dictionary plan merges per-process dictionaries with a
            # collective, ops/device_agg.py); a host gather of the global
            # column is impossible by construction, and column_values
            # raises the spans-processes error for them.
            host_block[name] = list(v)
            host_infos.append(ColumnInfo(name, dtype, Shape((Unknown,))))
            continue
        # cross-process array assembly blocks on every peer: under a
        # dispatch deadline a dead rank yields a named postmortem, not
        # an indefinite hang (name bound early: the lambda outlives the
        # loop iteration on the watchdog thread)
        arr = _fleet.run_with_deadline(
            lambda sh=batch_sharding(mesh, arr_np.ndim, axis), a=arr_np: (
                jax.make_array_from_process_local_data(sh, a)
            ),
            describe=f"distributed.frame_from_process_local[{name}]",
        )
        block[name] = arr
        infos.append(
            ColumnInfo(name, dtype, Shape(arr.shape).with_leading_unknown())
        )
    if not block:
        raise ValueError(
            "frame_from_process_local needs at least one device column "
            "(host-only columns cannot define the global row count)"
        )
    # device columns FIRST: the frame's row count reads the first column,
    # which must be a global array (host columns hold local rows only)
    block.update(host_block)
    frame = TensorFrame([block], Schema(infos + host_infos))
    frame._mesh = mesh
    frame._axis = axis
    frame._process_local_cols = frozenset(host_block)
    return frame
