"""Multi-host (multi-process) initialization.

The reference ships work to executors over Spark's cluster runtime; the
TPU-native equivalent is JAX's single-controller multi-process model: one
process per TPU host, all running the same program, glued by
``jax.distributed.initialize`` (DCN for control, ICI/DCN for collectives).
One Spark-executor-per-host maps to one-process-per-host (the BASELINE
north star's deployment shape).

On a single host this module is a no-op; every entry point is safe to call
unconditionally.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..utils import get_logger

logger = get_logger(__name__)

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> None:
    """Initialize multi-process JAX if configured (env vars or args).

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``); if neither args nor env are
    present this is a single-process no-op.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        logger.debug("init_distributed: single-process mode (no coordinator)")
        return
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0")
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    logger.info(
        "init_distributed: process %d/%d via %s",
        process_id,
        num_processes,
        coordinator_address,
    )


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()
