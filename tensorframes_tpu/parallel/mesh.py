"""Device mesh construction and axis conventions.

The reference's distributed substrate is Apache Spark: partitions +
broadcast + driver-coordinated reduce (SURVEY.md §5-comm). The TPU-native
substrate is a ``jax.sharding.Mesh`` over the chips of a slice, with data
laid out by ``NamedSharding`` and cross-chip traffic compiled to ICI
collectives by XLA's SPMD partitioner.

Axis naming conventions used across the framework:

* ``dp``  — data/batch parallelism (≙ Spark partitions; frames shard their
  row dimension here)
* ``tp``  — tensor parallelism (model weights; used by models/)
* ``sp``  — sequence/context parallelism (long-context attention)
* ``pp`` / ``ep`` — pipeline / expert parallelism (model-level)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import get_config

BATCH_AXIS = "dp"


def device_count() -> int:
    return len(jax.devices())


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh. Default: a 1-D data-parallel mesh over every device.

    ``axes`` maps axis name → size; one entry may be -1 meaning "all
    remaining devices". Example: ``make_mesh({"dp": -1})`` or
    ``make_mesh({"dp": 2, "tp": 4})``.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if not axes:
        axes = {BATCH_AXIS: n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                f"Cannot infer -1 axis: {n} devices not divisible by {known}"
            )
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"Mesh axes {dict(zip(names, sizes))} need "
            f"{math.prod(sizes)} devices but {n} are available"
        )
    # Auto axis types: XLA's SPMD partitioner solves intermediate shardings
    # (explicit sharding-in-types would demand out_sharding annotations on
    # ambiguous ops like embedding gathers).
    axis_types = (jax.sharding.AxisType.Auto,) * len(names)
    return jax.make_mesh(
        tuple(sizes), tuple(names), axis_types, devices=devices
    )


def batch_sharding(mesh: Mesh, rank: int, axis: Optional[str] = None) -> NamedSharding:
    """NamedSharding that splits the leading (row) dim over the batch axis
    and replicates the rest — the frame layout (≙ Spark row partitioning)."""
    axis = axis or get_config().batch_axis
    return NamedSharding(mesh, P(axis, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
