"""Device mesh construction and axis conventions.

The reference's distributed substrate is Apache Spark: partitions +
broadcast + driver-coordinated reduce (SURVEY.md §5-comm). The TPU-native
substrate is a ``jax.sharding.Mesh`` over the chips of a slice, with data
laid out by ``NamedSharding`` and cross-chip traffic compiled to ICI
collectives by XLA's SPMD partitioner.

Axis naming conventions used across the framework:

* ``dp``  — data/batch parallelism (≙ Spark partitions; frames shard their
  row dimension here)
* ``tp``  — tensor parallelism (model weights; used by models/)
* ``sp``  — sequence/context parallelism (long-context attention)
* ``pp`` / ``ep`` — pipeline / expert parallelism (model-level)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import get_config

BATCH_AXIS = "dp"


def _scrubbed(text: str) -> str:
    import re

    return re.sub(r"0x[0-9a-fA-F]+", "0x", text)


def mesh_descriptor(mesh: Mesh) -> Dict[str, object]:
    """JSON-able identity of a mesh for compile-cache keys: axis names +
    sizes and the global device assignment (ids are GLOBAL and agree on
    every process of a fleet, so the descriptor is process-index-
    independent by construction)."""
    return {
        "axes": [[str(n), int(s)] for n, s in
                 zip(mesh.axis_names, mesh.devices.shape)],
        "devices": [int(d.id) for d in mesh.devices.flat],
    }


def spec_descriptor(spec) -> list:
    """PartitionSpec → JSON-able form: one entry per dim, each None, an
    axis name, or a list of axis names."""
    out = []
    for part in tuple(spec):
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(p) for p in part])
        else:
            out.append(str(part))
    return out


def sharding_descriptor(sharding) -> Optional[Dict[str, object]]:
    """Stable JSON-able identity of an input sharding for dispatch keys
    and persistent-cache fingerprints — None for the trivial placement
    (single default device, or no sharding at all), so host-fed and
    plain single-device dispatches keep their unsharded identity.

    An AOT executable is specialized to its input shardings (calling it
    with differently-laid-out arguments raises), so everything that
    changes the layout must be in the key: mesh axis names + shape +
    device assignment and the per-dim partition spec for
    ``NamedSharding``; the concrete device for an off-default
    ``SingleDeviceSharding``; a scrubbed repr for exotic sharding types.
    """
    if sharding is None:
        return None
    SDS = getattr(jax.sharding, "SingleDeviceSharding", ())
    if isinstance(sharding, SDS):
        try:
            (dev,) = sharding.device_set
        except (ValueError, TypeError):  # pragma: no cover - defensive
            return {"type": "single", "repr": _scrubbed(repr(sharding))}
        # the default placement — where a fresh host transfer lands on
        # THIS process — keys identically to host feeds. That device is
        # the process-LOCAL default (jax.devices()[0] only equals it on
        # rank 0): comparing against the global device 0 would give every
        # other rank a device-bearing token for plain host feeds, so no
        # rank would ever share a store entry or match a warmed key.
        if dev == default_device():
            return None
        return {"type": "single", "device": int(dev.id)}
    if isinstance(sharding, NamedSharding):
        desc = {
            "type": "named",
            "mesh": mesh_descriptor(sharding.mesh),
            "spec": spec_descriptor(sharding.spec),
        }
        mk = getattr(sharding, "memory_kind", None)
        if mk is not None:
            desc["memory_kind"] = str(mk)
        return desc
    return {
        "type": type(sharding).__name__,
        "repr": _scrubbed(repr(sharding)),
        "devices": sorted(int(d.id) for d in sharding.device_set),
    }


def default_device():
    """Where an uncommitted host transfer lands on THIS process: the
    configured ``jax_default_device``, else the first process-local
    device. Descriptor/token caches key on it so a mid-process
    ``jax.config.update('jax_default_device', ...)`` is honored."""
    dd = getattr(jax.config, "jax_default_device", None)
    return dd if dd is not None else jax.local_devices()[0]


def device_count() -> int:
    return len(jax.devices())


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh. Default: a 1-D data-parallel mesh over every device.

    ``axes`` maps axis name → size; one entry may be -1 meaning "all
    remaining devices". Example: ``make_mesh({"dp": -1})`` or
    ``make_mesh({"dp": 2, "tp": 4})``.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if not axes:
        axes = {BATCH_AXIS: n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1")
    known = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                f"Cannot infer -1 axis: {n} devices not divisible by {known}"
            )
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(
            f"Mesh axes {dict(zip(names, sizes))} need "
            f"{math.prod(sizes)} devices but {n} are available"
        )
    # Auto axis types: XLA's SPMD partitioner solves intermediate shardings
    # (explicit sharding-in-types would demand out_sharding annotations on
    # ambiguous ops like embedding gathers). Version-tolerant: AxisType
    # (and make_mesh's axis_types parameter) only exist on newer jax —
    # older releases are Auto-only, so falling back to the 2-argument
    # form (or the raw Mesh constructor) is semantically identical.
    axis_type = getattr(jax.sharding, "AxisType", None)
    if axis_type is not None:
        try:
            return jax.make_mesh(
                tuple(sizes), tuple(names),
                (axis_type.Auto,) * len(names), devices=devices,
            )
        except TypeError:  # make_mesh predates the axis_types parameter
            pass
    try:
        return jax.make_mesh(tuple(sizes), tuple(names), devices=devices)
    except (AttributeError, TypeError):  # very old jax: no make_mesh
        import numpy as np

        return Mesh(np.asarray(devices).reshape(tuple(sizes)), tuple(names))


def batch_sharding(mesh: Mesh, rank: int, axis: Optional[str] = None) -> NamedSharding:
    """NamedSharding that splits the leading (row) dim over the batch axis
    and replicates the rest — the frame layout (≙ Spark row partitioning)."""
    axis = axis or get_config().batch_axis
    return NamedSharding(mesh, P(axis, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
