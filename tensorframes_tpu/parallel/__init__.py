from .distributed import init_distributed, is_multiprocess, process_index
from .mesh import BATCH_AXIS, batch_sharding, device_count, make_mesh, replicated

__all__ = [
    "BATCH_AXIS",
    "batch_sharding",
    "device_count",
    "init_distributed",
    "is_multiprocess",
    "make_mesh",
    "process_index",
    "replicated",
]
