from .distributed import (
    fleet_barrier,
    frame_from_process_local,
    init_distributed,
    is_multiprocess,
    process_index,
    process_topology,
)
from .mesh import (
    BATCH_AXIS,
    batch_sharding,
    device_count,
    make_mesh,
    mesh_descriptor,
    replicated,
    sharding_descriptor,
)
from .pipeline import make_pp_train_step, pipeline_apply

__all__ = [
    "make_pp_train_step",
    "pipeline_apply",
    "BATCH_AXIS",
    "batch_sharding",
    "device_count",
    "fleet_barrier",
    "init_distributed",
    "is_multiprocess",
    "frame_from_process_local",
    "make_mesh",
    "mesh_descriptor",
    "process_index",
    "process_topology",
    "replicated",
    "sharding_descriptor",
]
