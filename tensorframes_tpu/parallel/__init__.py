from .distributed import (
    fleet_barrier,
    frame_from_process_local,
    init_distributed,
    is_multiprocess,
    process_index,
)
from .mesh import BATCH_AXIS, batch_sharding, device_count, make_mesh, replicated
from .pipeline import make_pp_train_step, pipeline_apply

__all__ = [
    "make_pp_train_step",
    "pipeline_apply",
    "BATCH_AXIS",
    "batch_sharding",
    "device_count",
    "fleet_barrier",
    "init_distributed",
    "is_multiprocess",
    "frame_from_process_local",
    "make_mesh",
    "process_index",
    "replicated",
]
