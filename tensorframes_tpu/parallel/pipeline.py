"""Pipeline parallelism: a GPipe-style microbatch schedule over a mesh
``pp`` axis.

The reference has no pipeline parallelism (SURVEY.md §2.7: absent); this
extends the parallelism inventory the TPU way. No per-stage processes and
no send/recv runtime: all stages run the SAME jitted SPMD program under
``shard_map``, stage-to-stage activation transfer is a ``lax.ppermute``
ring shift over ICI, and the schedule is a ``lax.scan`` over
``num_microbatches + num_stages - 1`` ticks with static shapes —
compiler-friendly control flow throughout (no data-dependent Python).

The scan carries each device's in-flight activation; at tick ``t`` stage
``s`` computes microbatch ``t - s`` (bubble ticks compute garbage that is
masked out), then every device shifts its output one hop down the ring.
Stage 0 feeds from the microbatch queue; the last stage writes into the
output buffer, which a masked ``psum`` broadcasts to all shards at the
end. Differentiable end-to-end (``ppermute``/``scan`` have transposes),
so a full training step jits over pp × dp meshes.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._shard_map import shard_map as _shard_map
from ..utils import get_logger

logger = get_logger(__name__)


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    mesh: Mesh,
    axis: str = "pp",
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = "dp",
):
    """Run ``num_stages`` chained applications of ``stage_fn`` as a
    pipeline over the mesh's ``axis``.

    ``stage_fn(params_slice, h) -> h`` is one stage's computation; shapes
    of ``h`` must be stage-invariant (equal widths), the usual pipeline
    constraint. ``stage_params`` is a pytree whose leaves have a leading
    ``num_stages`` dim (stage ``s`` uses leaf[s]); it is sharded over
    ``axis`` so each device holds only its own stage's weights.
    ``x`` is [batch, ...]; it is split into ``num_microbatches`` equal
    microbatches (default: the pp degree). A ``batch_axis`` present on the
    mesh splits each microbatch data-parallel across it.

    Returns ``stage_{S-1}(... stage_0(x))`` replicated over ``axis``.
    """
    n_stages = mesh.shape[axis]
    for path, leaf in jax.tree_util.tree_flatten_with_path(stage_params)[0]:
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has leading "
                f"dim {leaf.shape[0]}, expected num_stages={n_stages} "
                f"(mesh axis {axis!r})"
            )
    m = num_microbatches or n_stages
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")
    mb = batch // m
    db = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    if db and mb % mesh.shape[db] != 0:
        logger.warning(
            "pipeline_apply: microbatch size %d not divisible by mesh axis "
            "%r=%d — falling back to replicated batches (every %s replica "
            "computes the full batch)",
            mb, db, mesh.shape[db], db,
        )
        db = None
    xs = x.reshape(m, mb, *x.shape[1:])

    def shard_fn(params_local, xs_full):
        # params_local: this stage's slice, leading dim 1 → squeeze
        params_local = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage_idx = lax.axis_index(axis)
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            h, out_buf = carry
            # stage 0 pulls microbatch t from the queue (clamped index;
            # bubble ticks recompute a stale microbatch and are masked out)
            mb_idx = jnp.clip(t, 0, m - 1)
            feed = lax.dynamic_index_in_dim(xs_full, mb_idx, keepdims=False)
            cur = jnp.where(stage_idx == 0, feed, h)
            y = stage_fn(params_local, cur)
            # last stage banks microbatch t - (S-1) when it's real
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            is_real = (t >= n_stages - 1) & (stage_idx == n_stages - 1)
            banked = lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(is_real, y, out_buf[out_idx]), out_idx, 0
            )
            # ring-shift activations one hop toward the next stage
            h_next = lax.ppermute(y, axis, perm=fwd)
            return (h_next, banked), None

        h0 = jnp.zeros_like(xs_full[0])
        out0 = jnp.zeros_like(xs_full)
        (_, out_buf), _ = lax.scan(
            tick, (h0, out0), jnp.arange(m + n_stages - 1)
        )
        # outputs live on the last stage only; masked psum broadcasts them
        mask = (stage_idx == n_stages - 1).astype(out_buf.dtype)
        return lax.psum(out_buf * mask, axis)

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    data_spec = P(None, db)  # microbatch dim whole, batch dim dp-split
    out = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec_params, data_spec),
        out_specs=data_spec,
        check=False,
    )(stage_params, xs)
    return out.reshape(batch, *x.shape[1:])


def make_pp_train_step(
    stage_fn: Callable,
    loss_head: Callable,
    mesh: Mesh,
    tx,
    axis: str = "pp",
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = "dp",
):
    """Jitted full training step for a pipelined model.

    ``loss_head(h, targets) -> scalar`` consumes the final stage output.
    Stage params are sharded over ``axis`` (leading stage dim); the batch
    is sharded over ``batch_axis`` so dp replicas each train on their own
    slice (jit inserts the gradient all-reduce). Gradients flow backward
    through the ppermute ring (XLA reverses the schedule).
    """
    db = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    data_sharding = NamedSharding(mesh, P(db) if db else P())

    def step(stage_params, opt_state, x, targets):
        import optax

        def loss_fn(p):
            out = pipeline_apply(
                stage_fn, p, x, mesh, axis=axis,
                num_microbatches=num_microbatches, batch_axis=db,
            )
            return loss_head(out, targets)

        loss, grads = jax.value_and_grad(loss_fn)(stage_params)
        updates, opt_state = tx.update(grads, opt_state, stage_params)
        stage_params = optax.apply_updates(stage_params, updates)
        return stage_params, opt_state, loss

    def param_shardings(stage_params):
        return jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P(axis)), stage_params
        )

    def jit_for(stage_params):
        sh = param_shardings(stage_params)
        init_opt = jax.jit(tx.init, in_shardings=(sh,))
        # unified AOT dispatch (ISSUE 10): the pp train step keys by its
        # mesh/sharding topology and restarts warm from the store
        from ..ops.executor import aot_jit

        jitted = aot_jit(
            step,
            in_shardings=(sh, None, data_sharding, data_sharding),
            out_shardings=(sh, None, NamedSharding(mesh, P())),
            label="pipeline.pp_train_step",
        )
        return jitted, init_opt, sh

    return jit_for
