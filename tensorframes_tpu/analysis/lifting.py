"""Static front-end of verified UDF lifting (Tenspiler-style, 2404.18249).

A numpy UDF captured as a host callback (``tfs.numpy_udf``) is the plan
layer's last hard fusion barrier: pushdown, join reordering and kernel
selection all decline around an opaque ``pure_callback`` stage (TFG107
names it). This module inspects the *Python source* of such a UDF and
either produces a :class:`LiftCandidate` — a validated AST restricted to
a closed allowlist of elementwise/reduction numpy ops, constants and
column refs (no control flow, no side effects, no mutable state) — or
raises :class:`LiftDeclined` naming the offending AST node.

The candidate is only half the story: :mod:`tensorframes_tpu.plan.lift`
synthesizes an equivalent pure-jnp Program from it and *verifies* the
synthesis bit-exactly against the original numpy function on a bounded
boundary-value corpus before any substitution happens. This module is
deliberately jax-free (pure ``ast``/``inspect``) so ``lint
--lift-report`` and the TFG112 rule can classify UDFs without touching a
backend.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

__all__ = [
    "LiftCandidate",
    "LiftDeclined",
    "inspect_udf",
    "ELEMENTWISE_OPS",
    "REDUCTION_OPS",
    "ARRAY_METHODS",
]

# ---------------------------------------------------------------------------
# The closed allowlist
# ---------------------------------------------------------------------------

#: ``np.<name>`` calls synthesized as elementwise plan-IR expressions.
#: Everything here has a 1:1 ``jnp`` counterpart; whether a given use
#: verifies bit-exactly on the actual block dtypes is decided by the
#: plan/lift equivalence harness, not here (libm-vs-XLA transcendental
#: ULP/NaN-payload differences are caught there, never papered over).
ELEMENTWISE_OPS: Set[str] = {
    "abs", "absolute",
    "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "mod", "remainder", "power",
    "negative", "positive", "sign",
    "exp", "expm1", "exp2", "log", "log1p", "log2", "log10",
    "sqrt", "square",
    "floor", "ceil", "trunc", "rint",
    "sin", "cos", "tan", "tanh", "sinh", "cosh",
    "arcsin", "arccos", "arctan",
    "maximum", "minimum", "where", "clip",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "isnan", "isinf", "isfinite",
}

#: Full reductions (block → scalar). Float-dtype reductions are
#: *policy-declined* downstream: ``sum``/``mean``/``prod`` because
#: numpy's pairwise accumulation order is not bit-stable against an XLA
#: reduce (the same exactness line the optimizer's reassoc_safe gate
#: draws), ``min``/``max`` because signed-zero ties at the extremum
#: resolve position-dependently in numpy and order-free in XLA. Integer
#: min/max, int/bool sum (modular), and narrow-int mean (exact f64
#: accumulation; int64 declines — inexact past 2^53) lift.
REDUCTION_OPS: Set[str] = {"sum", "mean", "prod", "min", "max", "amin", "amax"}

#: ndarray method spellings (``x.sum()``, ``x.clip(lo, hi)``) accepted as
#: aliases of the ``np.<name>`` call form.
ARRAY_METHODS: Set[str] = {"sum", "mean", "prod", "min", "max", "clip"}

_ALLOWED_BINOPS = {
    ast.Add: "add", ast.Sub: "subtract", ast.Mult: "multiply",
    ast.Div: "divide", ast.FloorDiv: "floor_divide", ast.Mod: "mod",
    ast.Pow: "power",
}
_ALLOWED_UNARY = {ast.USub: "negative", ast.UAdd: "positive",
                  ast.Invert: "invert"}
_ALLOWED_CMPOPS = (ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE)

#: Immutable scalar closure types that lift as compile-time constants.
_SCALAR_TYPES = (int, float, bool, complex)

#: Mutable closure types that make a callback a stale-closure hazard:
#: the callback re-reads them on every block, so a post-capture mutation
#: silently rebinds the UDF's behavior. Lift declines these loudly and
#: the capture path warns (TFG112).
_MUTABLE_TYPES_NAMES = (
    "list", "dict", "set", "bytearray", "ndarray", "defaultdict",
    "OrderedDict", "Counter", "deque",
)


class LiftDeclined(Exception):
    """A UDF the lifter refuses, with the taxonomy reason and — wherever
    one exists — the offending AST node (TFG112's explain()-with-fix
    names it)."""

    def __init__(self, reason: str, node: Optional[str] = None,
                 lineno: Optional[int] = None, detail: str = ""):
        self.reason = reason
        self.node = node
        self.lineno = lineno
        self.detail = detail
        loc = f" (line {lineno})" if lineno else ""
        at = f" at {node!r}" if node else ""
        super().__init__(f"{reason}{at}{loc}" + (f": {detail}" if detail else ""))


@dataclass
class LiftCandidate:
    """A UDF that passed static validation: its body is a straight-line
    sequence of allowlisted expressions over column refs, numeric
    constants and immutable scalar closures. Synthesis + bit-exact
    verification (plan/lift) still decide whether it actually lifts."""

    fn: object
    name: str
    source: str
    params: List[str]
    #: immutable scalar closure/global bindings, snapshotted at inspect
    consts: Dict[str, object]
    #: names bound to the numpy module inside the UDF ("np", "numpy")
    np_aliases: Set[str]
    #: straight-line body: zero or more single-target Assigns, then Return
    body: List[ast.stmt]
    #: syntactic evidence a full reduction appears (drives the corpus's
    #: empty-block handling: numpy min/max of an empty block raise, so
    #: the size-0 case is undefined for both paths alike)
    has_reduction: bool = False
    mutable_closures: List[str] = field(default_factory=list)


def _decline(reason: str, node: Optional[ast.AST] = None, detail: str = ""):
    name = type(node).__name__ if node is not None else None
    lineno = getattr(node, "lineno", None)
    raise LiftDeclined(reason, node=name, lineno=lineno, detail=detail)


def _get_source_tree(fn):
    """Source → AST for a def or a lambda. Lambdas come wrapped in their
    enclosing statement; locate the first Lambda node."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise LiftDeclined("no-source", detail=str(e))
    try:
        tree = ast.parse(src)
    except SyntaxError:
        # a lambda mid-expression can dedent into invalid syntax; retry
        # wrapped in parens
        try:
            tree = ast.parse(f"({src.strip()})", mode="eval")
        except SyntaxError as e:
            raise LiftDeclined("no-source", detail=f"unparseable source: {e}")
    if fn.__name__ == "<lambda>":
        for node in ast.walk(tree):
            if isinstance(node, ast.Lambda):
                return src, node
        raise LiftDeclined("no-source", detail="lambda source not found")
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(node, ast.AsyncFunctionDef):
                _decline("unsupported-syntax:AsyncFunctionDef", node)
            return src, node
    raise LiftDeclined("no-source", detail="no function definition in source")


def _closure_env(fn):
    """Snapshot the UDF's free/global bindings and classify each:
    numpy aliases, immutable scalar constants, or mutable hazards."""
    import numpy as np

    bindings: Dict[str, object] = {}
    code = getattr(fn, "__code__", None)
    if code is not None and fn.__closure__:
        for var, cell in zip(code.co_freevars, fn.__closure__):
            try:
                bindings[var] = cell.cell_contents
            except ValueError:  # empty cell
                continue
    g = getattr(fn, "__globals__", {}) or {}
    for name in (code.co_names if code is not None else ()):
        if name in g and name not in bindings:
            bindings[name] = g[name]

    np_aliases: Set[str] = set()
    consts: Dict[str, object] = {}
    mutable: List[str] = []
    for name, val in bindings.items():
        if val is np:
            np_aliases.add(name)
        elif isinstance(val, _SCALAR_TYPES) or isinstance(val, np.generic):
            consts[name] = val
        elif type(val).__name__ in _MUTABLE_TYPES_NAMES or isinstance(
            val, (list, dict, set, bytearray, np.ndarray)
        ):
            mutable.append(name)
        # anything else (modules, callables, objects) is only an offense
        # if the body actually references it — the validator decides
    return np_aliases, consts, mutable


class _Validator(ast.NodeVisitor):
    """Raise LiftDeclined on the first construct outside the allowlist.
    The taxonomy follows the TFG112 catalog: unsupported-syntax:<Node>,
    unsupported-call:<name>, mutable-closure:<var>,
    data-dependent-branch, augmented-assignment."""

    def __init__(self, cand: LiftCandidate, mutable: List[str]):
        self.c = cand
        self.mutable = set(mutable)
        self.locals: Set[str] = set(cand.params)

    # -- statements ---------------------------------------------------
    def check_body(self, stmts: List[ast.stmt]) -> List[ast.stmt]:
        body: List[ast.stmt] = []
        # a leading docstring is inert
        if stmts and isinstance(stmts[0], ast.Expr) and isinstance(
            stmts[0].value, ast.Constant
        ) and isinstance(stmts[0].value.value, str):
            stmts = stmts[1:]
        if not stmts:
            _decline("unsupported-syntax:empty-body")
        for i, st in enumerate(stmts):
            if isinstance(st, ast.Return):
                if st.value is None:
                    _decline("unsupported-syntax:bare-return", st)
                if i != len(stmts) - 1:
                    _decline("unsupported-syntax:early-return", st)
                self._check_return(st.value)
                body.append(st)
            elif isinstance(st, ast.Assign):
                if len(st.targets) != 1 or not isinstance(
                    st.targets[0], ast.Name
                ):
                    _decline("unsupported-syntax:Assign", st,
                             detail="only single-name targets lift")
                self.visit(st.value)
                self.locals.add(st.targets[0].id)
                body.append(st)
            elif isinstance(st, ast.AugAssign):
                _decline("augmented-assignment", st)
            elif isinstance(st, (ast.If,)):
                _decline("data-dependent-branch", st)
            else:
                _decline(f"unsupported-syntax:{type(st).__name__}", st)
        if not isinstance(body[-1], ast.Return):
            _decline("unsupported-syntax:no-return", body[-1])
        return body

    def _check_return(self, value: ast.expr) -> None:
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    _decline("unsupported-syntax:Dict", value,
                             detail="output dict keys must be string "
                                    "literals")
            for v in value.values:
                self.visit(v)
        elif isinstance(value, (ast.Tuple, ast.List)):
            for v in value.elts:
                self.visit(v)
        else:
            self.visit(value)

    # -- expressions --------------------------------------------------
    def visit_Name(self, node: ast.Name):
        if not isinstance(node.ctx, ast.Load):
            _decline(f"unsupported-syntax:{type(node.ctx).__name__}", node)
        nm = node.id
        if nm in self.locals or nm in self.c.consts or nm in self.c.np_aliases:
            return
        if nm in self.mutable:
            raise LiftDeclined(
                f"mutable-closure:{nm}", node="Name",
                lineno=node.lineno,
                detail=f"{nm!r} is mutable captured state — the callback "
                       "re-reads it per block (stale-closure hazard)")
        _decline("unsupported-syntax:Name", node,
                 detail=f"unknown or non-scalar reference {nm!r}")

    def visit_Constant(self, node: ast.Constant):
        if not isinstance(node.value, _SCALAR_TYPES):
            _decline("unsupported-syntax:Constant", node,
                     detail=f"{type(node.value).__name__} literal")

    def visit_BinOp(self, node: ast.BinOp):
        if type(node.op) not in _ALLOWED_BINOPS:
            _decline(f"unsupported-syntax:{type(node.op).__name__}", node)
        self.visit(node.left)
        self.visit(node.right)

    def visit_UnaryOp(self, node: ast.UnaryOp):
        if isinstance(node.op, ast.Not):
            _decline("data-dependent-branch", node,
                     detail="`not` takes array truthiness; use "
                            "np.logical_not")
        if type(node.op) not in _ALLOWED_UNARY:
            _decline(f"unsupported-syntax:{type(node.op).__name__}", node)
        self.visit(node.operand)

    def visit_Compare(self, node: ast.Compare):
        if len(node.ops) != 1:
            _decline("unsupported-syntax:chained-comparison", node)
        if not isinstance(node.ops[0], _ALLOWED_CMPOPS):
            _decline(f"unsupported-syntax:{type(node.ops[0]).__name__}",
                     node)
        self.visit(node.left)
        self.visit(node.comparators[0])

    def visit_BoolOp(self, node: ast.BoolOp):
        _decline("data-dependent-branch", node,
                 detail="`and`/`or` take array truthiness; use "
                        "np.logical_and / np.logical_or")

    def visit_IfExp(self, node: ast.IfExp):
        _decline("data-dependent-branch", node,
                 detail="conditional expression branches on data; use "
                        "np.where")

    def visit_Call(self, node: ast.Call):
        # classify the callee first so e.g. np.random.rand(*shape)
        # declines as unsupported-call:np.random.rand, not as the
        # incidental Starred argument
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self.c.np_aliases:
            # np.<name>(...)
            if f.attr in ELEMENTWISE_OPS:
                pass
            elif f.attr in REDUCTION_OPS:
                self.c.has_reduction = True
            else:
                raise LiftDeclined(
                    f"unsupported-call:np.{f.attr}", node="Call",
                    lineno=node.lineno)
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id in self.c.np_aliases:
            # np.random.rand(...) and friends: submodule calls never lift
            raise LiftDeclined(
                f"unsupported-call:np.{f.value.attr}.{f.attr}",
                node="Call", lineno=node.lineno)
        elif isinstance(f, ast.Attribute):
            # x.sum() method spelling: receiver must itself validate
            if f.attr not in ARRAY_METHODS:
                raise LiftDeclined(
                    f"unsupported-call:.{f.attr}", node="Call",
                    lineno=node.lineno)
            if f.attr != "clip":
                self.c.has_reduction = True
            self.visit(f.value)
        elif isinstance(f, ast.Name):
            if f.id == "abs":
                pass  # builtin abs maps to np.abs
            else:
                raise LiftDeclined(
                    f"unsupported-call:{f.id}", node="Call",
                    lineno=node.lineno)
        else:
            _decline("unsupported-syntax:Call", node)
        if node.keywords:
            _decline("unsupported-syntax:keyword-argument", node)
        for a in node.args:
            if isinstance(a, ast.Starred):
                _decline("unsupported-syntax:Starred", a)
            self.visit(a)

    def visit_Subscript(self, node: ast.Subscript):
        # indexing into a mutable closure (state[0], lut[k]) is the
        # stale-closure hazard itself — name it over the generic
        # Subscript decline
        if isinstance(node.value, ast.Name) and node.value.id in self.mutable:
            raise LiftDeclined(
                f"mutable-closure:{node.value.id}", node="Subscript",
                lineno=node.lineno,
                detail=f"{node.value.id!r} is mutable captured state — "
                       "the callback re-reads it per block "
                       "(stale-closure hazard)")
        _decline("unsupported-syntax:Subscript", node)

    def visit_Attribute(self, node: ast.Attribute):
        # bare attribute reads (x.T, x.shape, np.pi as value) — only
        # np.<scalar constant> style is conceivable but keep the door
        # closed until something needs it
        _decline("unsupported-syntax:Attribute", node)

    def generic_visit(self, node: ast.AST):
        if isinstance(node, (ast.Subscript, ast.Slice, ast.Lambda,
                             ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp, ast.Await, ast.Yield,
                             ast.YieldFrom, ast.NamedExpr, ast.JoinedStr,
                             ast.For, ast.While, ast.With, ast.Try,
                             ast.Global, ast.Nonlocal, ast.Raise,
                             ast.Assert, ast.Delete, ast.Import,
                             ast.ImportFrom, ast.ClassDef)):
            if isinstance(node, (ast.For, ast.While)):
                _decline(f"unsupported-syntax:{type(node).__name__}", node,
                         detail="loops do not lift")
            _decline(f"unsupported-syntax:{type(node).__name__}", node)
        super().generic_visit(node)


def inspect_udf(fn) -> LiftCandidate:
    """Validate ``fn``'s source against the lifting allowlist.

    Returns a :class:`LiftCandidate` on success; raises
    :class:`LiftDeclined` with a taxonomy reason + offending node
    otherwise. Purely static — never calls ``fn``.
    """
    src, tree = _get_source_tree(fn)
    np_aliases, consts, mutable = _closure_env(fn)

    if isinstance(tree, ast.Lambda):
        args = tree.args
        body_stmts: List[ast.stmt] = [ast.Return(value=tree.body)]
        ast.copy_location(body_stmts[0], tree.body)
        ast.fix_missing_locations(body_stmts[0])
    else:
        if tree.decorator_list:
            _decline("unsupported-syntax:decorator", tree)
        args = tree.args
        body_stmts = tree.body

    if args.vararg or args.kwarg or args.kwonlyargs or args.posonlyargs \
            or args.defaults or args.kw_defaults:
        _decline("unsupported-syntax:arguments", tree,
                 detail="only plain positional column-ref parameters lift")
    params = [a.arg for a in args.args]
    if not params:
        _decline("unsupported-syntax:arguments", tree,
                 detail="UDF takes no column inputs")

    cand = LiftCandidate(
        fn=fn,
        name=getattr(fn, "__name__", "<udf>"),
        source=src,
        params=params,
        consts=consts,
        np_aliases=np_aliases or {"np", "numpy"},
        body=[],
        mutable_closures=list(mutable),
    )
    v = _Validator(cand, mutable)
    cand.body = v.check_body(list(body_stmts))
    return cand


def detect_mutable_closures(fn) -> List[str]:
    """Names of mutable objects (list/dict/set/ndarray/…) the UDF closes
    over — the stale-closure hazard surface, reported even when the
    static validator declines for an earlier reason."""
    try:
        _, _, mutable = _closure_env(fn)
    except Exception:  # pragma: no cover - exotic callables
        return []
    return list(mutable)
