"""The rule catalog: pure functions from a traced-program context to
:class:`~tensorframes_tpu.analysis.diagnostics.Diagnostic` lists.

Every rule is grounded in a hazard this codebase has already paid for:

* **TFG101 recompile-storm** — unknown dims the executor's lead-dim
  bucket table (:func:`tensorframes_tpu.ops.executor.bucket_table`)
  cannot bound: inner Unknown dims compile one executable per distinct
  extent, and frames presenting ≥3 distinct block shapes storm the
  block-mode cache (SURVEY §7 hard-part 1; the r3 TPU collapse).
* **TFG102 f64-leak** — float64 creeping back in past the x64 demotion
  boundary (``config.demote_x64_on_tpu``): f64 is software-emulated on
  TPU, so one captured ``np.float64`` constant (the old DSL
  ``zeros``/``ones`` default) silently re-promotes the whole program.
* **TFG103 unused-input** — jaxpr invars consumed by no output still pay
  validation, marshalling and host→HBM transfer per block.
* **TFG104 donation-alias** — a donated feed kept as a column: XLA may
  reuse the donated input buffer for outputs, corrupting the kept data
  (the executor only guards *device-resident* columns at runtime).
* **TFG105 nan-hazard** — ``log``/``div``/``rsqrt``/``sqrt`` whose
  operand is not provably positive (resp. nonneg / nonzero) under a
  small sign-lattice walk of the jaxpr. ``resilience.guards.StepGuard``
  only catches the NaN *after* the step burned the accelerator time.
* **TFG106 hbm-budget** — static residency estimate (hoisted consts +
  probe-batch inputs + outputs) against the device memory budget, a
  warning *before* the first OOM instead of a crash after it.
* **TFG107 fusion-barrier** — a verb chain whose otherwise-fusable map
  stages are split by a fusion barrier (host callback, ``to_host`` /
  ``to_numpy`` materialization, ragged regrouping, trim): each split
  pays a fresh XLA dispatch plus intermediate materialization the plan
  layer (:mod:`tensorframes_tpu.plan`) would otherwise have fused away.
  Runs from :func:`~tensorframes_tpu.analysis.lint_plan` only — it
  needs a frame's plan chain, not a single program.

* **TFG108 cache-fingerprint-unstable** — the persistent compile
  cache's content hash differs across two identical rebuilds of the
  program (non-deterministically serialized captures): every process
  start misses the store and recompiles — a miss storm.
* **TFG111 larger-than-budget materialization** — a forced
  ``to_host``/``to_numpy`` whose estimated bytes
  (``estimated_rows`` × the schema row width) exceed the block-store
  budget (``TFTPU_BLOCK_BUDGET_MB``): the whole table lands in host
  RAM at once where the out-of-core data plane
  (:mod:`tensorframes_tpu.blockstore`) would stream it with bounded
  peak RSS. ``lint_plan`` only, like TFG107/109/110.

Rules never execute or compile anything: they read specs, the traced
jaxpr, and config. Tracing itself (``jax.make_jaxpr``) happens once in
:mod:`.analyzer` (TFG108 adds two more traces to probe rebuild
stability — still zero compiles).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..shape import Unknown
from .diagnostics import Diagnostic

__all__ = ["RuleContext", "RULES", "run_rules"]


@dataclasses.dataclass
class RuleContext:
    """Everything a rule may read. ``closed`` is the program's
    ``ClosedJaxpr`` (None when tracing failed — spec-level rules still
    run); ``in_names``/``in_avals`` follow the jaxpr's flat invar order."""

    program: object
    probe: int = 8
    closed: object = None
    in_names: Sequence[str] = ()
    in_avals: Sequence[object] = ()
    out_names: Sequence[str] = ()
    out_avals: Sequence[object] = ()
    #: True for block-mode use, False for row-mode, None when unknown.
    block_mode: Optional[bool] = None
    #: Distinct block row counts of an already-materialized frame
    #: (None when no frame context / frame is lazy — never forces one).
    block_row_counts: Optional[Tuple[int, ...]] = None
    hbm_budget_bytes: Optional[int] = None
    trace_error: Optional[BaseException] = None
    #: Fusion barriers found on a frame's plan chain (lint_plan only):
    #: dicts with ``reason``, ``upstream_maps``, ``downstream_maps``.
    plan_barriers: Optional[Sequence[dict]] = None
    #: Aggregate/join epilogues that stayed a barrier for a FUSABLE
    #: reason (lint_plan only): dicts with ``verb``, ``reason`` —
    #: recorded by plan.ir.mark_unfused, read by TFG109.
    unfused_epilogues: Optional[Sequence[dict]] = None
    #: Fixable causes blocking an aggregate-below-join pushdown
    #: (lint_plan only): dicts with ``cause``, ``subject``, ``detail``,
    #: ``fix`` — the static eligibility walk's findings
    #: (plan.rules.plan_pushdown) plus runtime causes recorded by
    #: plan.ir.mark_pushdown_miss; read by TFG110.
    pushdown_misses: Optional[Sequence[dict]] = None
    #: Forced to_host/to_numpy materializations whose estimated bytes
    #: exceed the block-store budget (lint_plan only): dicts with
    #: ``reason``, ``estimated_bytes``, ``budget_bytes``, ``rows`` —
    #: plan.lower.oversized_materializations; read by TFG111.
    oversized_materializations: Optional[Sequence[dict]] = None
    #: Ambient mesh for sharded programs (``analyze_frame`` passes the
    #: frame's mesh): TFG108's stability probes re-trace under it, so
    #: programs using collectives/sharding constraints lint instead of
    #: silently skipping. Tracing stays abstract — no device transfers.
    mesh: object = None
    #: Input-name → sharding the executor will dispatch with (sharded
    #: frames: the batch sharding per device column). Part of the
    #: probed cache key — the fingerprint must be stable WITH the
    #: layout axes in it, exactly as the store keys executables.
    shardings: Optional[Dict[str, object]] = None
    #: Verified-lift decisions for numpy UDF stages on a frame's plan
    #: chain (lint_plan only): the capture records attached by
    #: plan.lift.build_udf_program — dicts with ``udf``, ``lifted``,
    #: ``reason``, ``node``, ``lineno``, ``detail``; read by TFG112.
    lift_events: Optional[Sequence[dict]] = None
    #: Prefix-cache ineligibility evidence from the decode engines
    #: (lint_plan only): dicts with ``endpoint``, ``reason``,
    #: ``prompt_len``, ``page_size`` — recorded per (endpoint, reason)
    #: by serving.decode, read by TFG113.
    prefix_cache_events: Optional[Sequence[dict]] = None
    #: Registered-query decline evidence (lint_plan only): dicts with
    #: ``endpoint``, ``mode`` ('cache' | 'incremental'), ``reason``,
    #: ``detail`` — recorded per (endpoint, mode, reason) by
    #: serving.query when a registered pipeline's plan blocks result
    #: caching or incremental maintenance; read by TFG114.
    query_cache_events: Optional[Sequence[dict]] = None


# ---------------------------------------------------------------------------
# jaxpr walking helpers (version-tolerant: duck-typed Literal / sub-jaxpr)
# ---------------------------------------------------------------------------

def _is_literal(v) -> bool:
    """jax Literals carry ``val``; Vars carry ``aval`` only."""
    return hasattr(v, "val")


def _literal_value(v):
    return np.asarray(v.val)


def _sub_jaxprs(eqn):
    """Yield (jaxpr, consts) for any sub-jaxpr in the eqn's params
    (pjit / custom_jvp_call / scan / while …)."""
    for p in eqn.params.values():
        if hasattr(p, "jaxpr") and hasattr(p, "consts"):  # ClosedJaxpr
            yield p.jaxpr, p.consts
        elif hasattr(p, "eqns") and hasattr(p, "invars"):  # raw Jaxpr
            yield p, ()


def _iter_eqns(jaxpr):
    """Depth-first over eqns, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub, _ in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


# ---------------------------------------------------------------------------
# TFG101 — recompile-storm
# ---------------------------------------------------------------------------

def _rule_recompile_storm(ctx: RuleContext) -> List[Diagnostic]:
    from ..ops.executor import bucket_table

    out: List[Diagnostic] = []
    table = bucket_table()
    head = ", ".join(str(b) for b in table[:6]) + ("…" if len(table) > 6 else "")
    for spec in ctx.program.inputs:
        dims = spec.shape.dims
        if any(d == Unknown for d in dims[1:]):
            out.append(Diagnostic(
                "TFG101", "warn",
                f"input {spec.name!r} has unknown non-leading dim(s) in "
                f"{spec.shape}: the executor buckets only the LEAD dim "
                f"(bucket table: [{head}]), so every distinct inner extent "
                "triggers a fresh XLA compile",
                subject=spec.name,
                fix="pin the inner dims in the placeholder/TensorSpec (or "
                    "pad the data to a fixed extent) so the jit cache stays "
                    "O(log n) instead of O(#shapes)",
            ))
        if dims and dims[0] == Unknown and len(table) <= 1:
            out.append(Diagnostic(
                "TFG101", "warn",
                f"input {spec.name!r} has an unknown batch dim but lead-dim "
                "bucketing is disabled (config.max_bucket_doublings <= 0): "
                "every distinct row count compiles fresh",
                subject=spec.name,
                fix="re-enable bucketing (configure(max_bucket_doublings=...)"
                    ") or feed fixed-size blocks",
            ))
    if (
        ctx.block_mode is True
        and ctx.block_row_counts is not None
        and len(set(ctx.block_row_counts)) >= 3
    ):
        sizes = sorted(set(ctx.block_row_counts))
        shown = ", ".join(str(s) for s in sizes[:6])
        out.append(Diagnostic(
            "TFG101", "warn",
            f"frame presents {len(sizes)} distinct block row counts "
            f"([{shown}{'…' if len(sizes) > 6 else ''}]); block-mode "
            "dispatch compiles one executable per distinct shape — the "
            "bucket table bounds map_rows only",
            subject="frame",
            fix="repartition() the frame (the partitioner yields at most "
                "two block sizes) or switch to map_rows, whose vmapped "
                "lead dim is bucketed",
        ))
    return out


# ---------------------------------------------------------------------------
# TFG102 — f64-leak
# ---------------------------------------------------------------------------

def _rule_f64_leak(ctx: RuleContext) -> List[Diagnostic]:
    from .. import dtypes as dt

    if ctx.closed is None:
        return []
    f64 = np.dtype(np.float64)
    if any(np.dtype(a.dtype) == f64 for a in ctx.in_avals):
        return []  # a genuinely-f64 program: nothing is "leaking"
    demoting = dt.demotion_active()
    severity = "warn" if demoting else "info"
    boundary = (
        "re-promotes past the active x64 demotion boundary "
        "(config.demote_x64_on_tpu)" if demoting
        else "promotes an otherwise sub-64-bit program to float64"
    )
    out: List[Diagnostic] = []
    jaxpr = ctx.closed.jaxpr
    for var, const in zip(jaxpr.constvars, ctx.closed.consts):
        dtype = getattr(const, "dtype", None)
        if dtype is not None and np.dtype(dtype) == f64:
            shape = tuple(getattr(const, "shape", ()))
            out.append(Diagnostic(
                "TFG102", severity,
                f"captured float64 constant (shape {shape}) {boundary}",
                subject=f"const{shape}",
                fix="build the constant at the framework dtype policy — "
                    "dsl.zeros/ones/fill now default to dtypes."
                    "default_float(); for raw numpy use dtype=np.float32 "
                    "(or dtypes.default_float().np_dtype)",
            ))
    seen = 0
    for i, eqn in enumerate(_iter_eqns(jaxpr)):
        in_f64 = any(
            not _is_literal(v) and np.dtype(v.aval.dtype) == f64
            for v in eqn.invars
            if hasattr(v, "aval") or _is_literal(v)
        )
        out_f64 = any(
            np.dtype(v.aval.dtype) == f64
            for v in eqn.outvars if hasattr(v, "aval")
        )
        if out_f64 and not in_f64:
            seen += 1
            if seen > 8:  # cap the spam; the first sites locate the leak
                break
            out.append(Diagnostic(
                "TFG102", severity,
                f"{eqn.primitive.name} at eqn#{i} emits float64 from "
                f"non-float64 operands — {boundary}",
                subject=f"eqn#{i}:{eqn.primitive.name}",
                fix="pin the op's dtype (e.g. dtype=jnp.float32) or drop "
                    "the float64 literal feeding it",
            ))
    return out


# ---------------------------------------------------------------------------
# TFG103 — unused-input
# ---------------------------------------------------------------------------

def _rule_unused_input(ctx: RuleContext) -> List[Diagnostic]:
    if ctx.closed is None:
        return []
    jaxpr = ctx.closed.jaxpr
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not _is_literal(v):
                used.add(id(v))
    for v in jaxpr.outvars:
        if not _is_literal(v):
            used.add(id(v))
    out: List[Diagnostic] = []
    for name, var in zip(ctx.in_names, jaxpr.invars):
        if id(var) not in used:
            out.append(Diagnostic(
                "TFG103", "info",
                f"input {name!r} is consumed by no output (dead fetch): it "
                "still pays schema validation, marshalling and host→HBM "
                "transfer on every block",
                subject=name,
                fix=f"drop {name!r} from the program's inputs (or from the "
                    "feed_dict) so the column never ships to the device",
            ))
    return out


# ---------------------------------------------------------------------------
# TFG104 — donation-alias
# ---------------------------------------------------------------------------

def _rule_donation_alias(ctx: RuleContext) -> List[Diagnostic]:
    from ..config import get_config

    in_names = [s.name for s in ctx.program.inputs]
    out_names = (
        [s.name for s in ctx.program.outputs]
        if ctx.program.outputs else list(ctx.out_names)
    )
    clash = sorted(set(in_names) & set(out_names))
    if not clash:
        return []
    donating = get_config().donate_inputs
    severity = "error" if donating else "info"
    state = (
        "input donation is enabled (config.donate_inputs)" if donating
        else "input donation is currently disabled, but enabling it would "
             "corrupt the kept column"
    )
    return [Diagnostic(
        "TFG104", severity,
        f"feed(s) {clash} are also kept as output column(s) while {state}: "
        "XLA may reuse a donated input buffer for an output, so the kept "
        "column can alias freed memory",
        subject=",".join(clash),
        fix="fetch the column under a different output name (e.g. via "
            "identity(...).named('x_out')) or run with "
            "configure(donate_inputs=False)",
    )]


# ---------------------------------------------------------------------------
# TFG105 — nan-hazard (sign-lattice walk)
# ---------------------------------------------------------------------------

_POS, _NONNEG, _UNK = "positive", "nonnegative", "unknown"


def _sign_of_value(val) -> str:
    try:
        arr = np.asarray(val)
        if arr.size == 0 or arr.dtype.kind not in "ifub":
            return _UNK
        if np.all(arr > 0):
            return _POS
        if np.all(arr >= 0):
            return _NONNEG
    except Exception:
        pass
    return _UNK


def _sign_of_aval(aval) -> str:
    dtype = np.dtype(getattr(aval, "dtype", np.float32))
    if dtype.kind in ("u", "b"):  # unsigned ints / bools
        return _NONNEG
    return _UNK


def _join2(a: str, b: str, table: Dict[Tuple[str, str], str]) -> str:
    return table.get((a, b)) or table.get((b, a)) or _UNK


_ADD = {(_POS, _POS): _POS, (_POS, _NONNEG): _POS, (_NONNEG, _NONNEG): _NONNEG}
_MUL = {(_POS, _POS): _POS, (_POS, _NONNEG): _NONNEG,
        (_NONNEG, _NONNEG): _NONNEG}
_MAX = {(_POS, _POS): _POS, (_POS, _NONNEG): _POS, (_POS, _UNK): _POS,
        (_NONNEG, _NONNEG): _NONNEG, (_NONNEG, _UNK): _NONNEG}

#: primitives that preserve their (single) operand's sign
_SIGN_PRESERVING = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims", "transpose",
    "slice", "dynamic_slice", "convert_element_type", "copy",
    "stop_gradient", "reduce_max", "reduce_min", "rev", "gather",
})


def _meet(signs) -> str:
    """Strongest sign every element of a mixed bag satisfies (used for
    concatenate: the result is only as positive as its WEAKEST operand)."""
    signs = list(signs)
    if signs and all(s == _POS for s in signs):
        return _POS
    if signs and all(s in (_POS, _NONNEG) for s in signs):
        return _NONNEG
    return _UNK

#: hazard primitive → (operand index, sign required to be safe, hazard text)
_HAZARDS = {
    "log": (0, _POS, "log of a non-positive value is -inf/NaN"),
    "div": (1, _POS, "division by a value not provably nonzero"),
    "rsqrt": (0, _POS, "rsqrt of a non-positive value is inf/NaN"),
    "sqrt": (0, _NONNEG, "sqrt of a negative value is NaN"),
}

_SAFE_REQ = {_POS: (_POS,), _NONNEG: (_POS, _NONNEG)}


def _nonzero_of_value(val) -> bool:
    try:
        arr = np.asarray(val)
        return arr.size > 0 and arr.dtype.kind in "ifub" and bool(
            np.all(arr != 0)
        )
    except Exception:
        return False


def _walk_signs(jaxpr, consts, init_env, hazards, depth=0):
    """Forward sign pass over one jaxpr; appends (site, prim, sign, text)
    hazard tuples. ``init_env`` maps var id → sign for the invars. A
    parallel nonzero lattice covers the div hazard for values that are
    provably nonzero without being positive (e.g. a ``-2.0`` literal)."""
    env: Dict[int, str] = dict(init_env)
    nz: Dict[int, bool] = {}
    for var, const in zip(jaxpr.constvars, consts):
        env[id(var)] = _sign_of_value(const)
        nz[id(var)] = _nonzero_of_value(const)

    def sign_of(v) -> str:
        if _is_literal(v):
            return _sign_of_value(_literal_value(v))
        return env.get(id(v), _UNK)

    def nonzero_of(v) -> bool:
        if _is_literal(v):
            return _nonzero_of_value(_literal_value(v))
        return nz.get(id(v), False) or env.get(id(v), _UNK) == _POS

    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        ins = [sign_of(v) for v in eqn.invars]
        ins_nz = [nonzero_of(v) for v in eqn.invars]
        if name in _HAZARDS:
            idx, need, text = _HAZARDS[name]
            got = ins[idx] if idx < len(ins) else _UNK
            safe = got in _SAFE_REQ[need]
            if name == "div" and idx < len(ins_nz) and ins_nz[idx]:
                safe = True  # nonzero (even negative) denominator: no NaN
            if not safe:
                hazards.append((f"eqn#{i}:{name}", name, got, text))
        # transfer
        res_nz = False
        if name == "exp":
            res = _POS
            res_nz = True
        elif name == "neg":
            res = _UNK
            res_nz = ins_nz[0] if ins_nz else False
        elif name == "concatenate":
            # only as positive as the WEAKEST operand (a single possibly-
            # negative part poisons the whole result)
            res = _meet(ins)
            res_nz = bool(ins_nz) and all(ins_nz)
        elif name in ("abs", "square"):
            res = _POS if ins and ins[0] == _POS else _NONNEG
            res_nz = ins_nz[0] if ins_nz else False
        elif name == "integer_pow":
            y = eqn.params.get("y", 0)
            if y % 2 == 0:
                res = _POS if ins and ins[0] == _POS else _NONNEG
            else:
                res = ins[0] if ins else _UNK
        elif name in ("add", "reduce_sum"):
            res = ins[0] if len(ins) == 1 else _join2(ins[0], ins[1], _ADD)
            if name == "reduce_sum" and res == _POS:
                # an empty reduction yields 0, degrading POS to NONNEG —
                # but reduced extents are concrete at trace time, so a
                # provably non-empty sum of positives stays positive
                # (softmax denominators: sum(exp(x)) over a real axis)
                axes = eqn.params.get("axes", ())
                shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
                nonempty = all(
                    0 <= ax < len(shape) and shape[ax] > 0 for ax in axes
                ) if axes else True
                if not nonempty:
                    res = _NONNEG
        elif name in ("mul", "div"):
            res = _join2(ins[0], ins[1], _MUL) if len(ins) == 2 else _UNK
            res_nz = len(ins_nz) == 2 and all(ins_nz)
        elif name == "max":
            res = _join2(ins[0], ins[1], _MAX) if len(ins) == 2 else _UNK
        elif name in ("sqrt", "rsqrt"):
            res = ins[0] if ins and ins[0] in (_POS, _NONNEG) else _UNK
            if name == "rsqrt" and res == _NONNEG:
                res = _UNK  # rsqrt(0) = inf
            res_nz = ins_nz[0] if ins_nz else False
        elif name in _SIGN_PRESERVING:
            res = ins[0] if ins else _UNK
            res_nz = ins_nz[0] if ins_nz else False
        else:
            # opaque primitive: recurse into any sub-jaxpr so hazards
            # inside pjit/custom_jvp bodies still surface; result UNK
            for sub, sub_consts in _sub_jaxprs(eqn):
                if depth < 4 and len(sub.invars) == len(eqn.invars):
                    sub_env = {
                        id(sv): s for sv, s in zip(sub.invars, ins)
                    }
                    _walk_signs(
                        sub, sub_consts, sub_env, hazards, depth + 1
                    )
            res = _UNK
        for ov in eqn.outvars:
            env[id(ov)] = res
            nz[id(ov)] = res_nz or res == _POS


def _rule_nan_hazard(ctx: RuleContext) -> List[Diagnostic]:
    if ctx.closed is None:
        return []
    init = {
        id(v): _sign_of_aval(v.aval)
        for v in ctx.closed.jaxpr.invars
    }
    hazards: List[Tuple[str, str, str, str]] = []
    _walk_signs(ctx.closed.jaxpr, ctx.closed.consts, init, hazards)
    out: List[Diagnostic] = []
    for site, prim, got, text in hazards[:8]:
        out.append(Diagnostic(
            "TFG105", "warn",
            f"{text} (operand sign statically {got}) at {site}",
            subject=site,
            fix="clamp or guard the operand before the op (e.g. "
                "jnp.maximum(x, eps), jnp.where(mask, x, safe)); "
                "resilience.guards.StepGuard only catches the NaN after "
                "the step already ran",
        ))
    return out


# ---------------------------------------------------------------------------
# TFG106 — hbm-budget
# ---------------------------------------------------------------------------

def _aval_bytes(avals) -> int:
    total = 0
    for a in avals:
        shape = tuple(getattr(a, "shape", ()))
        dtype = np.dtype(getattr(a, "dtype", np.float32))
        total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return total


def _device_budget_bytes() -> Optional[int]:
    """``bytes_limit`` of the first addressable device, when the backend
    reports memory stats (TPU/GPU do; XLA:CPU returns None)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit")
            return int(limit) if limit else None
    except Exception:
        return None
    return None


def _rule_hbm_budget(ctx: RuleContext) -> List[Diagnostic]:
    if ctx.closed is None:
        return []
    budget = ctx.hbm_budget_bytes
    if budget is None:
        budget = _device_budget_bytes()
    if not budget:
        return []  # no budget known: the rule has nothing to compare against
    const_b = _aval_bytes(ctx.closed.consts)
    in_b = _aval_bytes(ctx.in_avals)
    out_b = _aval_bytes(ctx.out_avals)
    est = const_b + in_b + out_b
    # prefer XLA's own numbers when a cost analysis was already memoized
    # (cost_analysis COMPILES, so the rule never triggers one itself)
    cost_note = ""
    cache = getattr(ctx.program, "_cost_cache", None)
    if cache:
        peak = max(
            (float(c.get("bytes accessed", 0.0)) for c in cache.values()),
            default=0.0,
        )
        if peak:
            cost_note = (
                f"; memoized XLA cost model reports {peak / 1e6:.1f} MB "
                "accessed"
            )
            est = max(est, int(peak))
    if est <= budget:
        return []
    return [Diagnostic(
        "TFG106", "warn",
        f"static residency estimate {est / 1e6:.1f} MB (consts "
        f"{const_b / 1e6:.1f} + inputs {in_b / 1e6:.1f} + outputs "
        f"{out_b / 1e6:.1f} MB at probe batch {ctx.probe}) exceeds the "
        f"device budget {budget / 1e6:.1f} MB{cost_note} — expect OOM "
        "before the first result",
        subject="program",
        fix="shrink the per-call batch (more blocks / smaller buckets), "
            "quantize the weights (int8 keeps s8 residency under the "
            "hoisted path), or shard the frame over more chips",
    )]


# ---------------------------------------------------------------------------
# TFG107 — fusion-barrier (plan-chain rule: lint_plan only)
# ---------------------------------------------------------------------------

def _rule_fusion_barrier(ctx: RuleContext) -> List[Diagnostic]:
    if not ctx.plan_barriers:
        return []
    out: List[Diagnostic] = []
    for b in ctx.plan_barriers:
        up = int(b.get("upstream_maps", 0))
        down = int(b.get("downstream_maps", 0))
        if up + down < 1:
            continue  # a barrier with no fusable neighbor splits nothing
        up_txt = str(up) if b.get("upstream_exact", True) else f">={up}"
        out.append(Diagnostic(
            "TFG107", "warn",
            f"chain contains a fusion barrier — {b['reason']} — between "
            f"otherwise-fusable map stages ({up_txt} upstream, {down} "
            "downstream): each side dispatches its own XLA program and "
            "the boundary materializes every intermediate column",
            subject=str(b["reason"]).split(":")[0],
            fix="move the barrier out of the hot chain (materialize once "
                "up front, run analyze() to densify ragged columns, keep "
                "host callbacks out of chained stages), or accept the "
                "split — the plan layer already fuses each side "
                "separately",
        ))
    return out


# ---------------------------------------------------------------------------
# TFG109 — unfused-aggregate (plan-chain rule: lint_plan only)
# ---------------------------------------------------------------------------

def _rule_unfused_aggregate(ctx: RuleContext) -> List[Diagnostic]:
    """An ``aggregate``/``join`` consuming an otherwise-fusable lazy
    chain stayed a fusion barrier for a reason the USER can fix (the
    plan layer records only fixable causes — mandatory fallbacks like
    sharded/multi-process feeds are honest and never flagged): the
    chain materialized its mapped columns and the epilogue dispatched
    separately instead of composing into the per-block program."""
    if not ctx.unfused_epilogues:
        return []
    out: List[Diagnostic] = []
    for e in ctx.unfused_epilogues:
        out.append(Diagnostic(
            "TFG109", "warn",
            f"{e['verb']} stayed a fusion barrier on an otherwise-"
            f"fusable chain — {e['reason']} — so the upstream mapped "
            "columns materialized and the epilogue dispatched as a "
            "separate program instead of fusing into one dispatch per "
            "block",
            subject=str(e["verb"]),
            fix=str(e["reason"]),
        ))
    return out


# ---------------------------------------------------------------------------
# TFG110 — missed-aggregate-pushdown (plan-chain rule: lint_plan only)
# ---------------------------------------------------------------------------

def _rule_missed_pushdown(ctx: RuleContext) -> List[Diagnostic]:
    """An aggregate sits above a join the adaptive optimizer could push
    it below — the rows would then never match-expand — but a *fixable*
    cause blocks the rewrite: an order-sensitive float fetch, a group
    key set that does not cover the join key, fetches mixing both join
    sides, an outer join, or (recorded at force time) duplicate
    build-side keys. Each finding names the blocking column/fetch and
    the fix. Mandatory exclusions (sharded/multi-process feeds,
    ``TFTPU_REOPT=0``) are honest, not fixable, and never flagged."""
    if not ctx.pushdown_misses:
        return []
    out: List[Diagnostic] = []
    for m in ctx.pushdown_misses:
        out.append(Diagnostic(
            "TFG110", "warn",
            "aggregate sits above a join it could push below, but "
            f"{m.get('detail', m.get('cause', 'an unknown cause'))} — "
            "so every row match-expands through the join and the "
            "epilogue reduces the expanded table instead of the "
            "pre-join partials",
            subject=str(m.get("subject", "aggregate")),
            fix=str(m.get("fix", "")),
        ))
    return out


# ---------------------------------------------------------------------------
# TFG111 — larger-than-budget materialization (plan-chain rule: lint_plan)
# ---------------------------------------------------------------------------

def _rule_oversized_materialization(ctx: RuleContext) -> List[Diagnostic]:
    """A forced ``to_host``/``to_numpy`` materialized an estimated byte
    volume past the block-store budget (``TFTPU_BLOCK_BUDGET_MB``):
    the whole table landed in host RAM at once, which is exactly the
    workload the out-of-core data plane streams with bounded peak RSS
    instead (docs/dataplane.md)."""
    if not ctx.oversized_materializations:
        return []
    out: List[Diagnostic] = []
    for m in ctx.oversized_materializations:
        est_mb = m["estimated_bytes"] / (1 << 20)
        bud_mb = m["budget_bytes"] / (1 << 20)
        out.append(Diagnostic(
            "TFG111", "warn",
            f"forced materialization ({m['reason']}) holds an estimated "
            f"{est_mb:.0f} MiB ({m['rows']:,} rows) in host RAM at once "
            f"— past the {bud_mb:.0f} MiB block-store budget "
            "(TFTPU_BLOCK_BUDGET_MB)",
            subject="to_host",
            fix="stream instead of materializing: walk the chain with "
                "blockstore.stream_chain(io.scan_csv/scan_parquet(...), "
                "chain_fn, fold_fn=...) — results spill to the block "
                "store as they complete and peak RSS stays under the "
                "budget — or spill the frame explicitly with "
                "frame.spill_to(BlockStore()); raise "
                "TFTPU_BLOCK_BUDGET_MB only if the host genuinely has "
                "the RAM",
        ))
    return out


# ---------------------------------------------------------------------------
# TFG108 — cache-fingerprint-unstable (persistent-cache miss storm)
# ---------------------------------------------------------------------------

def _unstable_axis_evidence(ctx: RuleContext) -> str:
    """Name the sharding axis implicated in a jaxpr-component
    instability: re-trace under the mesh until a pair of rebuilds
    differs (the instability is by definition non-deterministic, so a
    single pair can coincide), diff the scrubbed jaxpr texts
    line-by-line, and compare the ``PartitionSpec(...)`` annotations on
    the first differing line — a sharding constraint that flips axes
    between rebuilds prints its spec into the jaxpr. Empty when no
    differing pair was seen or the diff names no spec."""
    import re as _re

    import jax

    from ..compilecache.fingerprint import _scrub
    from ..parallel._shard_map import mesh_context
    from ..program import _abstract_inputs

    spec_re = _re.compile(r"PartitionSpec\([^)]*\)")
    try:
        abstract = _abstract_inputs(ctx.program.inputs, ctx.probe)

        def trace_text() -> str:
            def rebuilt(feeds):
                return ctx.program.fn(feeds)

            with mesh_context(ctx.mesh):
                return _scrub(str(jax.make_jaxpr(rebuilt)(abstract).jaxpr))

        first = trace_text()
        second = first
        for _ in range(4):
            second = trace_text()
            if second != first:
                break
        if second == first:
            return ""
        for la, lb in zip(first.splitlines(), second.splitlines()):
            if la == lb:
                continue
            sa, sb = spec_re.findall(la), spec_re.findall(lb)
            if sa != sb and (sa or sb):
                axes = sorted(
                    set(_re.findall(r"'([^']+)'", " ".join(sa)))
                    ^ set(_re.findall(r"'([^']+)'", " ".join(sb)))
                )
                named = f" (unstable axis: {'/'.join(axes)})" if axes \
                    else ""
                return (" — a sharding annotation flips between "
                        f"rebuilds: {' '.join(sa) or '<none>'} vs "
                        f"{' '.join(sb) or '<none>'}{named}")
            return (f" — first differing trace line: {la.strip()!r} vs "
                    f"{lb.strip()!r}")
    except Exception:  # pragma: no cover - evidence is best-effort
        pass
    return ""


def _rule_fingerprint_unstable(ctx: RuleContext) -> List[Diagnostic]:
    """The persistent compile cache (tensorframes_tpu/compilecache)
    keys executables by a content hash of the traced program — since
    the unified AOT dispatch (ISSUE 10), with the mesh/sharding/
    topology axes in the key. A program whose fingerprint differs
    across two *identical* rebuilds — a captured constant produced by
    unseeded randomness at trace time, any capture that serializes
    non-deterministically, or a sharding annotation whose axes flip
    between rebuilds — can never hit the store: every process start
    (every RANK of every restart, for a fleet) recompiles everything
    it ships — a miss storm. Two independent traces here, run under
    the program's mesh context for sharded programs, with zero
    compiles and zero device transfers (``value_policy='host_only'``
    keeps device-resident captures out of the value hash — the one
    blind spot: a PLAIN-form program whose device-resident capture
    VALUES differ per process start misses the store without tripping
    this rule; hoist or seed such captures);
    :func:`~tensorframes_tpu.compilecache.fingerprint.fingerprint_components`
    names the component that moved instead of an opaque hash."""
    if ctx.program is None or ctx.closed is None:
        return []
    from ..compilecache.fingerprint import program_fingerprint

    kw = dict(probe=ctx.probe, mesh=ctx.mesh, shardings=ctx.shardings)
    a = program_fingerprint(ctx.program, components=True, **kw)
    b = program_fingerprint(ctx.program, components=True, **kw)
    if a is None or b is None or a == b:
        return []
    moved = [k for k in ("jaxpr", "consts", "avals", "outs", "env")
             if a.get(k) != b.get(k)]
    sh_moved = sorted(
        n for n in set(a.get("shardings", {})) | set(b.get("shardings", {}))
        if a.get("shardings", {}).get(n) != b.get("shardings", {}).get(n)
    )
    moved += [f"shardings[{n}]" for n in sh_moved]
    evidence = ""
    if ctx.mesh is not None and "jaxpr" in moved:
        evidence = _unstable_axis_evidence(ctx)
    what = {
        "jaxpr": "the traced jaxpr itself (trace-time control flow or "
                 "annotations differ between rebuilds)",
        "consts": "a captured constant serializes non-deterministically",
        "avals": "the abstract input signature",
        "outs": "the fetch order",
        "env": "the environment component",
    }
    detail = "; ".join(what.get(m, m) for m in moved)
    return [Diagnostic(
        "TFG108", "warn",
        "cache fingerprint differs across two identical rebuilds of "
        f"this program (unstable component(s): {', '.join(moved)} — "
        f"{detail}{evidence}): the persistent compile cache "
        "(TFTPU_COMPILE_CACHE) misses on every process start — a "
        "miss storm that recompiles from scratch each launch, on "
        "every rank of a sharded fleet",
        subject="program",
        fix="make trace-time captures deterministic (seed the RNG that "
            "builds captured arrays, avoid set/dict-order-dependent "
            "constructions, pick sharding/partition axes from a fixed "
            "list rather than an unordered collection); closure values "
            "and sharding annotations must be a pure function of the "
            "program definition for the cache key to be stable",
    )]


# ---------------------------------------------------------------------------
# TFG112 — liftable-callback / lift-declined (plan-chain rule)
# ---------------------------------------------------------------------------

def _rule_liftable_callback(ctx: RuleContext) -> List[Diagnostic]:
    """Verified UDF lifting decisions on the chain's numpy UDF stages
    (plan/lift): a *lifted* stage is an info — the callback barrier was
    cleared after bit-exact verification and the stage fuses like any
    other; a *declined* stage is a warn carrying the taxonomy reason
    and, where one exists, the offending AST node — the actionable
    rewrite that would let the UDF lift."""
    if not ctx.lift_events:
        return []
    out: List[Diagnostic] = []
    for ev in ctx.lift_events:
        udf = str(ev.get("udf", "<udf>"))
        if ev.get("lifted"):
            out.append(Diagnostic(
                "TFG112", "info",
                f"numpy UDF {udf!r} lifted into the plan IR (synthesis "
                "verified bit-exact on the boundary corpus): the stage "
                "fuses — no callback barrier, no per-stage dispatch",
                subject=udf,
                fix="none needed — TFTPU_LIFT=0 replays the callback "
                    "path if you need the host-side original",
            ))
            continue
        reason = str(ev.get("reason", "unknown"))
        node = ev.get("node")
        lineno = ev.get("lineno")
        at = f" at AST node {node}" if node else ""
        at += f" (line {lineno})" if lineno else ""
        detail = str(ev.get("detail") or "")
        out.append(Diagnostic(
            "TFG112", "warn",
            f"numpy UDF {udf!r} stayed a host-callback barrier — "
            f"lift declined: {reason}{at}"
            + (f" — {detail}" if detail else ""),
            subject=udf,
            fix="restrict the UDF to the lifting allowlist (elementwise "
                "numpy ops, min/max and int/bool sum/mean reductions, "
                "constants, column refs — no loops, branches, mutable "
                "closures or np.random); see docs/analysis.md#tfg112 "
                "for the full table, or keep the callback and accept "
                "the barrier",
        ))
    return out


# ---------------------------------------------------------------------------
# TFG113 — prefix-cache ineligible (serving evidence rule)
# ---------------------------------------------------------------------------

_TFG113_FIXES = {
    "store_unarmed":
        "arm the cache: register_decode(..., DecodeConfig("
        "prefix_cache=True)) — repeated prompt prefixes were observed, "
        "so those prefill chunks would be shared (docs/serving.md#kv-"
        "memory-hierarchy)",
    "page_misalignment":
        "prompts this short never fill one KV page, so nothing can be "
        "published or matched at page granularity — lower "
        "DecodeConfig.page_size below the common prefix length (the "
        "cache matches whole pages only)",
    "sampling_state_mismatch":
        "replay-resumed joins must reproduce their recorded tokens "
        "against the page state of first admission, so they bypass the "
        "cache by design — size the pool (num_pages) or arm kv_swap so "
        "fewer sequences resume through the recompute path",
}


def _rule_prefix_cache_ineligible(ctx: RuleContext) -> List[Diagnostic]:
    """Decode-engine evidence that prompt prefill work could NOT ride
    the content-addressed prefix cache: the cache was off while
    repeated prefixes arrived (store_unarmed), prompts were too short
    to fill one page (page_misalignment), or joins were replay-resumed
    and therefore pinned to their recorded state
    (sampling_state_mismatch). Each finding's fix names the config
    change — or explains why the exclusion is structural."""
    if not ctx.prefix_cache_events:
        return []
    out: List[Diagnostic] = []
    for ev in ctx.prefix_cache_events:
        reason = str(ev.get("reason", "unknown"))
        endpoint = str(ev.get("endpoint", "<endpoint>"))
        out.append(Diagnostic(
            "TFG113", "warn",
            f"decode endpoint {endpoint!r}: prompt prefill was not "
            f"shareable through the prefix cache — {reason} "
            f"(prompt_len={ev.get('prompt_len')}, "
            f"page_size={ev.get('page_size')})",
            subject=endpoint,
            fix=_TFG113_FIXES.get(
                reason,
                "see docs/analysis.md#tfg113 for the reason taxonomy",
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# TFG114 — registered query not cacheable/incremental (serving evidence)
# ---------------------------------------------------------------------------

_TFG114_FIXES = {
    "host_callback":
        "the map stage runs a host callback, so results are not a pure "
        "function of the plan fingerprint — lift the UDF "
        "(plan.lift/TFG112 names whether it is liftable) or move the "
        "callback out of the served pipeline",
    "non_algebraic":
        "the aggregate fetches a non-algebraic reduction, so it "
        "executes on the host instead of the plan — restrict fetches "
        "to sum/min/max/mean over the grouped column "
        "(docs/plan.md#incremental-partials)",
    "eager":
        "build() returned an already-materialized frame (no recorded "
        "plan chain) — return the LAZY verb chain without forcing it "
        "(no collect()/column_values inside build), and check "
        "TFTPU_FUSION is not disabled",
    "join":
        "per-chunk partials of a join-then-aggregate are not "
        "maintained (build-side changes would stale them silently) — "
        "pre-join into the scanned table, or accept counted full "
        "recompute per refresh",
    "computed_key":
        "the group key is computed by a map stage, so a chunk's key "
        "set is not a pure function of the chunk — materialize the "
        "key into the source table so it passes through the scan",
    "reduce_mean":
        "a mean only folds across chunks as a (sum, count) companion "
        "pair, which partial tables do not carry — aggregate "
        "reduce_sum and a count column instead and divide at read "
        "time",
    "float_accumulation":
        "float sums reassociate across chunk partials, so the fold "
        "would not be bit-identical to full recompute — cast the "
        "summed column to an integer dtype, or accept counted full "
        "recompute (min/max stay incremental at any dtype)",
    "no_terminal_aggregate":
        "only terminal keyed aggregates fold incrementally — end the "
        "registered chain in aggregate(...), or accept that refreshes "
        "re-execute the whole pipeline (repeat queries still cache)",
}


def _rule_query_not_incremental(ctx: RuleContext) -> List[Diagnostic]:
    """Registered-query evidence that the served pipeline degraded to
    counted full recompute: the plan blocks the result cache
    (mode='cache' — every request re-executes) or incremental
    maintenance (mode='incremental' — refreshes pay O(table) while
    repeats still cache). The fix names the blocking stage and the
    plan change that restores O(new data) refreshes."""
    if not ctx.query_cache_events:
        return []
    out: List[Diagnostic] = []
    for ev in ctx.query_cache_events:
        reason = str(ev.get("reason", "unknown"))
        endpoint = str(ev.get("endpoint", "<endpoint>"))
        mode = str(ev.get("mode", "cache"))
        what = ("result caching" if mode == "cache"
                else "incremental refresh")
        out.append(Diagnostic(
            "TFG114", "warn",
            f"query endpoint {endpoint!r}: plan blocks {what} — "
            f"{reason}: {ev.get('detail', '')}",
            subject=endpoint,
            fix=_TFG114_FIXES.get(
                reason,
                "see docs/analysis.md#tfg114 for the reason taxonomy",
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

RULES: Dict[str, Callable[[RuleContext], List[Diagnostic]]] = {
    "TFG101": _rule_recompile_storm,
    "TFG102": _rule_f64_leak,
    "TFG103": _rule_unused_input,
    "TFG104": _rule_donation_alias,
    "TFG105": _rule_nan_hazard,
    "TFG106": _rule_hbm_budget,
    "TFG107": _rule_fusion_barrier,
    "TFG108": _rule_fingerprint_unstable,
    "TFG109": _rule_unfused_aggregate,
    "TFG110": _rule_missed_pushdown,
    "TFG111": _rule_oversized_materialization,
    "TFG112": _rule_liftable_callback,
    "TFG113": _rule_prefix_cache_ineligible,
    "TFG114": _rule_query_not_incremental,
}


def run_rules(
    ctx: RuleContext, codes: Optional[Sequence[str]] = None
) -> List[Diagnostic]:
    """Run the selected rules (all by default) over one context. A rule
    that raises is a bug in the rule, not the user's program — it
    degrades to a single info diagnostic naming itself, so lint can
    never make a valid program un-runnable."""
    out: List[Diagnostic] = []
    for code, rule in RULES.items():
        if codes is not None and code not in codes:
            continue
        try:
            out.extend(rule(ctx))
        except Exception as e:  # pragma: no cover - rule bug safety net
            out.append(Diagnostic(
                code, "info",
                f"rule crashed ({type(e).__name__}: {e}); finding skipped",
                subject="analyzer",
                fix="report this as an analyzer bug",
            ))
    return out
