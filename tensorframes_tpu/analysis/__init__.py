"""tfguard: pre-execution static diagnostics over captured Programs.

The TPU-native stack validates "will it run" before execution
(:mod:`tensorframes_tpu.validation`, ≙ the reference's
``SchemaTransforms``); this package answers "will it run *well*" —
statically, from the captured jaxpr + specs, before the first
(expensive) XLA compile. See docs/analysis.md for the rule catalog.

Surfaces:

* :func:`lint_program` / ``Program.lint()`` — lint one program;
* :func:`analyze_frame` — lint fetches against a frame, normalized
  exactly as the verbs would run them;
* :func:`lint_plan` — lint a frame's logical plan chain (TFG107
  fusion barriers between otherwise-fusable maps);
* ``python -m tensorframes_tpu.analysis`` — lint serialized StableHLO
  bundles (CLI);
* ``strict=True`` on the verbs — raise
  :class:`~tensorframes_tpu.validation.StaticAnalysisError` on any
  error-severity diagnostic before dispatch.
"""

from .analyzer import analyze_frame, lint_plan, lint_program  # noqa: F401
from .diagnostics import (  # noqa: F401
    CODES,
    Diagnostic,
    DiagnosticReport,
    save_jsonl,
)

__all__ = [
    "CODES",
    "Diagnostic",
    "DiagnosticReport",
    "analyze_frame",
    "lint_plan",
    "lint_program",
    "save_jsonl",
]
