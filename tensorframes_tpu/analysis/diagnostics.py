"""Structured diagnostics: the value objects of the static analyzer.

A :class:`Diagnostic` is one finding of the pre-execution pass
(:mod:`tensorframes_tpu.analysis.analyzer`): a **stable code** (``TFG###``
— codes are API, dashboards and suppressions key on them), a severity
(``error`` | ``warn`` | ``info``), a one-line message bound to a concrete
subject (an input name, a jaxpr primitive site), and an ``explain()``
that adds the fix suggestion and the rule-catalog pointer.

Every diagnostic increments a **pre-registered** counter in
:mod:`tensorframes_tpu.observability.metrics`, labeled by code — the
whole family is registered at import (one series per known code), so a
Prometheus exposition always carries the full catalog: a fleet whose
programs never tripped ``TFG102`` reads 0 for it, the series does not
vanish. A bounded in-process log keeps the most recent diagnostics for
the CI artifact (``save_jsonl``), mirroring the metrics/trace exports.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import deque
from typing import Deque, Dict, Iterable, List, Tuple

from ..observability.metrics import counter as _counter

__all__ = [
    "CODES",
    "SEVERITIES",
    "Diagnostic",
    "DiagnosticReport",
    "DIAGNOSTIC_LOG",
    "save_jsonl",
]

#: Severity names, most severe first (ordering is part of the contract:
#: ``strict=`` raises on ``error`` only).
SEVERITIES: Tuple[str, ...] = ("error", "warn", "info")

#: The rule catalog: code → (title, default severity). Codes are stable
#: API — never renumber; retire by removing the rule but keeping the row.
CODES: Dict[str, Tuple[str, str]] = {
    "TFG101": ("recompile-storm", "warn"),
    "TFG102": ("f64-leak", "warn"),
    "TFG103": ("unused-input", "info"),
    "TFG104": ("donation-alias", "error"),
    "TFG105": ("nan-hazard", "warn"),
    "TFG106": ("hbm-budget", "warn"),
    "TFG107": ("fusion-barrier", "warn"),
    "TFG108": ("cache-fingerprint-unstable", "warn"),
    "TFG109": ("unfused-aggregate", "warn"),
    "TFG110": ("missed-aggregate-pushdown", "warn"),
    "TFG111": ("larger-than-budget-materialization", "warn"),
    # liftable-callback / lift-declined pair: info when a captured numpy
    # UDF lifted (verified bit-exact, barrier cleared), warn when it
    # stayed a callback — the message carries the taxonomy reason and
    # names the offending AST node.
    "TFG112": ("liftable-callback", "warn"),
    # prefix-cache ineligible: serving evidence that decode prefill
    # work could not be shared (repeated prefixes on an engine with the
    # cache off, prompts below one page, replay-resumed joins) — the
    # fix names the DecodeConfig/page-size change that would enable it.
    "TFG113": ("prefix-cache-ineligible", "warn"),
    # registered-query degradation: serving evidence that a pipeline
    # served via Server.register_query cannot ride the result cache or
    # refresh incrementally (host callback, non-algebraic fetch,
    # computed key, float accumulation, …) — the fix names the plan
    # change that restores O(new data) refreshes.
    "TFG114": ("query-not-incremental", "warn"),
    # TFL: the repo self-lint family (python -m tensorframes_tpu.analysis
    # selfcheck — policy rules over this repo's own sources, not user
    # programs). Registered here so one catalog covers every code a CI
    # log can print.
    "TFL001": ("bare-jax-jit", "error"),
    "TFL002": ("unguarded-module-state", "error"),
    "TFL003": ("unregistered-runtime-metric", "error"),
}

# Pre-register the full counter family at import: one series per code,
# so expositions carry every code from process start (ISSUE 3 contract;
# same convention as the executor/resilience instruments).
_DIAG_COUNTERS = {
    code: _counter(
        "tftpu_analysis_diagnostics_total",
        "Static diagnostics emitted by tensorframes_tpu.analysis, by code",
        labels={"code": code},
    )
    for code in CODES
}

#: Bounded log of recent diagnostics (CI exports it as
#: ``tier1_diagnostics.jsonl`` next to the metrics artifact). Lints may
#: run from verb worker threads; ``_LOG_LOCK`` serializes append vs the
#: export's snapshot iteration.
DIAGNOSTIC_LOG: Deque["Diagnostic"] = deque(maxlen=4096)
_LOG_LOCK = threading.Lock()


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One static finding. Immutable; ordering key is severity rank."""

    code: str
    severity: str
    message: str
    subject: str = ""  # input/output name or jaxpr site the finding binds to
    fix: str = ""  # one actionable suggestion

    def __post_init__(self):
        if self.code not in CODES:
            raise ValueError(f"unknown diagnostic code {self.code!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    @property
    def title(self) -> str:
        return CODES[self.code][0]

    def oneline(self) -> str:
        subj = f" [{self.subject}]" if self.subject else ""
        return f"{self.code} {self.severity}{subj}: {self.message}"

    def explain(self) -> str:
        """Message + fix suggestion + rule-catalog pointer."""
        lines = [self.oneline()]
        if self.fix:
            lines.append(f"  fix: {self.fix}")
        lines.append(
            f"  rule: {self.title} — docs/analysis.md#{self.code.lower()}"
        )
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _severity_rank(sev: str) -> int:
    return SEVERITIES.index(sev)


class DiagnosticReport:
    """The ordered findings of one lint run (most severe first).

    Construction is the single emission point: counters increment and
    the bounded log appends here, so every surface (API, CLI, strict
    verbs) feeds the same telemetry.
    """

    def __init__(self, diagnostics: Iterable[Diagnostic], subject: str = ""):
        self.diagnostics: List[Diagnostic] = sorted(
            diagnostics, key=lambda d: (_severity_rank(d.severity), d.code)
        )
        self.subject = subject
        with _LOG_LOCK:
            for d in self.diagnostics:
                _DIAG_COUNTERS[d.code].inc()
                DIAGNOSTIC_LOG.append(d)

    # -- access -------------------------------------------------------------
    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warn"]

    @property
    def infos(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "info"]

    def counts_by_severity(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for d in self.diagnostics:
            out[d.severity] += 1
        return out

    # -- rendering ----------------------------------------------------------
    def pretty(self, explain: bool = False) -> str:
        head = self.subject or "program"
        if not self.diagnostics:
            return f"{head}: clean (0 diagnostics)"
        c = self.counts_by_severity()
        lines = [
            f"{head}: {len(self)} diagnostic(s) "
            f"(error={c['error']} warn={c['warn']} info={c['info']})"
        ]
        for d in self.diagnostics:
            lines.append(d.explain() if explain else d.oneline())
        return "\n".join(lines)

    def to_jsonl(self) -> str:
        rows = [
            json.dumps({"subject": self.subject, **d.to_dict()}, sort_keys=True)
            for d in self.diagnostics
        ]
        return "\n".join(rows) + ("\n" if rows else "")

    # -- strict mode --------------------------------------------------------
    def raise_on_errors(self) -> "DiagnosticReport":
        """Raise :class:`~tensorframes_tpu.validation.StaticAnalysisError`
        when any error-severity diagnostic is present (the ``strict=``
        contract on the verbs); returns self otherwise so calls chain."""
        errs = self.errors
        if errs:
            from ..observability import flight as _flight
            from ..validation import StaticAnalysisError

            err = StaticAnalysisError(
                "static analysis found "
                f"{len(errs)} error-severity diagnostic(s):\n"
                + "\n".join(d.explain() for d in errs),
                diagnostics=errs,
            )
            # strict-mode rejection is a flight-recorder dump trigger:
            # the black box shows what dispatched before the program
            # that failed the gate, even when the caller catches this
            _flight.record(
                "static_analysis.error", subject=self.subject,
                codes=",".join(sorted({d.code for d in errs})),
                count=len(errs),
            )
            _flight.dump(reason="static-analysis", exc=err)
            raise err
        return self


def save_jsonl(path: str, clear: bool = False) -> int:
    """Write the bounded diagnostic log as JSONL (one object per line);
    returns the number of rows written. The CI tier-1 job exports this
    next to the metrics artifact."""
    with _LOG_LOCK:
        rows = [json.dumps(d.to_dict(), sort_keys=True) for d in DIAGNOSTIC_LOG]
        if clear:
            DIAGNOSTIC_LOG.clear()
    with open(path, "w") as f:
        f.write("\n".join(rows) + ("\n" if rows else ""))
    return len(rows)
