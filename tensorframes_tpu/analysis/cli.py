"""``python -m tensorframes_tpu.analysis`` — lint serialized programs.

Positional arguments are paths to serialized StableHLO program bundles
(written by :func:`tensorframes_tpu.save_program`); each is loaded with
:func:`~tensorframes_tpu.program.load_program` and linted **without
compiling or executing it** (deserialization + tracing only).

``selfcheck`` as the first argument dispatches to the repo self-lint
instead (:mod:`.selfcheck` — the TFL rules that used to live in
``dev/lint_rules.py``), making this module the ONE lint entry point CI
calls: ``python -m tensorframes_tpu.analysis selfcheck [paths]``.

``--demo`` builds the stock example programs (the README add-3 map, the
logreg scoring program, the geom-mean log-transform) in-process, lints
them, round-trips one through a temporary StableHLO bundle, and lints
that too — the CI lint job runs this over a checkout with no fixtures
on disk.

Exit status: 0 on success; with ``--strict``, 1 when any error-severity
diagnostic was found; 2 on unreadable inputs.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from .analyzer import lint_program

__all__ = ["main"]


def _lint_path(path: str, args) -> "tuple[int, int]":
    """Lint one bundle file; returns (n_errors, exit_hint)."""
    from ..program import load_program

    try:
        program = load_program(path)
    except Exception as e:
        print(f"{path}: cannot load program bundle ({type(e).__name__}: {e})",
              file=sys.stderr)
        return 0, 2
    report = lint_program(
        program,
        probe=args.probe,
        hbm_budget_bytes=args.hbm_budget,
        subject=path,
    )
    _emit(report, args)
    return len(report.errors), 0


def _emit(report, args) -> None:
    if args.json:
        payload = {
            "subject": report.subject,
            "counts": report.counts_by_severity(),
            "diagnostics": [d.to_dict() for d in report.diagnostics],
        }
        print(json.dumps(payload, sort_keys=True))
    else:
        print(report.pretty(explain=args.explain))


def _demo_reports(args) -> List:
    """The built-in example programs (mirrors examples/: the README
    add-3 quickstart, examples/train_logreg.py's scoring program, and
    examples/geom_mean.py's log-transform), each normalized through
    compile_program — tracing/eval_shape only, never an XLA compile."""
    import os
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu.models import logreg

    reports = []

    frame = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}, num_blocks=2
    )
    add3 = tfs.compile_program(lambda x: {"z": x + 3.0}, frame)
    reports.append(lint_program(add3, subject="examples: README add-3",
                                hbm_budget_bytes=args.hbm_budget))

    feats, _ = logreg.make_synthetic_mnist(8)
    lr_frame = tfs.frame_from_arrays({"features": feats})
    scoring = logreg.scoring_program(logreg.init_params())
    lr_prog = tfs.compile_program(
        lambda features: scoring(features), lr_frame
    )
    reports.append(lint_program(lr_prog, subject="examples: logreg scoring",
                                hbm_budget_bytes=args.hbm_budget))

    gm_frame = tfs.frame_from_arrays(
        {"v": np.asarray([1.0, 2.0, 4.0], np.float64)}
    )
    with tfs.with_graph():
        v = tfs.block(gm_frame, "v")
        fetch = tfs.apply_fn(jnp.log, v, name="t")
        gm_prog = tfs.compile_program(fetch, gm_frame)
    reports.append(lint_program(
        gm_prog, subject="examples: geom-mean log transform",
        hbm_budget_bytes=args.hbm_budget,
    ))

    # round-trip: export the add-3 program to a StableHLO bundle and lint
    # the *file*, exercising the same path the positional arguments take
    tmp = tempfile.mkdtemp(prefix="tfguard_demo.")
    bundle = os.path.join(tmp, "add3.stablehlo")
    try:
        tfs.save_program(add3, bundle)
        loaded = tfs.load_program(bundle)
        reports.append(lint_program(
            loaded, subject=f"examples: reloaded bundle {bundle}",
            hbm_budget_bytes=args.hbm_budget,
        ))
    finally:
        try:
            os.remove(bundle)
            os.rmdir(tmp)
        except OSError:
            pass
    return reports


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "selfcheck":
        # repo self-lint (TFL rules): one lint entry point for CI
        from .selfcheck import main as selfcheck_main

        return selfcheck_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m tensorframes_tpu.analysis",
        description="Statically lint serialized StableHLO program bundles "
                    "(no compile, no execution).",
    )
    parser.add_argument("paths", nargs="*",
                        help="program bundles written by tfs.save_program")
    parser.add_argument("--demo", action="store_true",
                        help="lint the built-in example programs (CI mode)")
    parser.add_argument("--json", action="store_true",
                        help="one JSON object per linted subject")
    parser.add_argument("--explain", action="store_true",
                        help="include fix suggestions and rule pointers")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when any error-severity diagnostic fires")
    parser.add_argument("--probe", type=int, default=8,
                        help="rows substituted for Unknown dims (default 8)")
    parser.add_argument("--hbm-budget", type=int, default=None,
                        help="device memory budget in bytes for TFG106 "
                             "(default: the backend's reported limit)")
    parser.add_argument("--lift-report", action="store_true",
                        help="print this process's verified-lift decisions "
                             "(lifted / declined + reason) and exit")
    args = parser.parse_args(argv)
    if args.lift_report:
        from ..plan import lift as plan_lift

        print(plan_lift.lift_report())
        return 0
    if not args.paths and not args.demo:
        parser.error("nothing to lint: pass bundle paths or --demo")

    n_errors = 0
    rc = 0
    if args.demo:
        for report in _demo_reports(args):
            _emit(report, args)
            n_errors += len(report.errors)
    for path in args.paths:
        errs, hint = _lint_path(path, args)
        n_errors += errs
        rc = max(rc, hint)
    if rc:
        return rc
    if args.strict and n_errors:
        return 1
    return 0
