"""The pre-execution static pass: trace once, run the rule catalog.

``lint_program(program)`` traces the program's function to a jaxpr with
``jax.make_jaxpr`` against abstract inputs (the same probe substitution
:func:`~tensorframes_tpu.program.analyze_program` uses) and hands the
result to every rule in :mod:`.rules`. **No execution, no XLA compile,
no device transfer** — tracing builds avals only, which is why a lint
of any program leaves the executor's jit-cache and compile-seconds
metrics untouched (the acceptance check in tests/test_analysis.py).

Three surfaces share this pass:

* ``program.lint(...)`` / ``lint_program(program, ...)`` — the API;
* ``analyze_frame(frame, fetches, ...)`` — lints fetches *against a
  frame* (schema-normalized exactly as the verbs would run them, plus
  frame-level context such as distinct block shapes — without forcing
  a lazy frame);
* ``python -m tensorframes_tpu.analysis`` — lints serialized StableHLO
  bundles from disk (see :mod:`.cli`).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .diagnostics import DiagnosticReport
from .rules import RuleContext, run_rules

__all__ = ["lint_program", "analyze_frame", "lint_plan"]


def _trace(program, probe: int):
    """Trace ``program.fn`` at abstract probe inputs. Returns
    (closed_jaxpr, in_names, in_avals, out_names, out_avals, error);
    on failure everything except the error is empty and spec-level
    rules still run."""
    import jax

    from ..program import _abstract_inputs

    abstract = _abstract_inputs(program.inputs, probe)
    try:
        closed, out_shape = jax.make_jaxpr(program.fn, return_shape=True)(
            abstract
        )
    except Exception as e:
        return None, (), (), (), (), e
    in_paths = jax.tree_util.tree_flatten_with_path(abstract)[0]
    in_names = [_path_leaf_name(p) for p, _ in in_paths]
    in_avals = [leaf for _, leaf in in_paths]
    out_paths = jax.tree_util.tree_flatten_with_path(out_shape)[0]
    out_names = [_path_leaf_name(p) for p, _ in out_paths]
    out_avals = [leaf for _, leaf in out_paths]
    return closed, in_names, in_avals, out_names, out_avals, None


def _path_leaf_name(path) -> str:
    """Render one pytree path to the dict key users named the tensor."""
    if not path:
        return "<out>"
    last = path[-1]
    key = getattr(last, "key", None)
    if key is None:
        key = getattr(last, "idx", None)
    return str(key) if key is not None else str(last)


def _effective_program(program):
    """Mirror the verb path's x64 demotion: lint traces the program at
    the same (possibly demoted) input dtypes the executor will feed."""
    from .. import dtypes as dt
    from ..program import Program, TensorSpec

    if not dt.demotion_active():
        return program
    if all(dt.demote(s.dtype) is s.dtype for s in program.inputs):
        return program
    demoted = [
        TensorSpec(s.name, dt.demote(s.dtype), s.shape)
        for s in program.inputs
    ]
    eff = Program(program.fn, demoted, program.outputs or None,
                  fetch_order=program.fetch_order)
    eff._cost_cache = getattr(program, "_cost_cache", None) or {}
    return eff


def lint_program(
    program,
    probe: int = 8,
    rules: Optional[Sequence[str]] = None,
    block_mode: Optional[bool] = None,
    block_row_counts: Optional[Tuple[int, ...]] = None,
    hbm_budget_bytes: Optional[int] = None,
    subject: str = "",
    mesh=None,
    shardings=None,
) -> DiagnosticReport:
    """Statically lint a :class:`~tensorframes_tpu.program.Program`.

    ``rules`` selects diagnostic codes (default: all). ``probe``
    substitutes Unknown dims for the trace (≙ analyze_program).
    ``hbm_budget_bytes`` overrides the device budget for TFG106 (by
    default the first device's reported ``bytes_limit``; the rule is
    silent when the backend reports none, as XLA:CPU does).
    ``mesh``/``shardings`` lint a program as its SHARDED dispatches
    run: TFG108's stability probes re-trace under the mesh context
    with the per-input shardings in the probed cache key (still zero
    compiles, zero device transfers) — ``analyze_frame`` fills both
    from a sharded frame automatically.
    """
    from ..parallel._shard_map import mesh_context

    eff = _effective_program(program)
    with mesh_context(mesh):
        closed, in_names, in_avals, out_names, out_avals, err = _trace(
            eff, probe
        )
    ctx = RuleContext(
        program=eff,
        probe=probe,
        closed=closed,
        in_names=in_names,
        in_avals=in_avals,
        out_names=out_names,
        out_avals=out_avals,
        block_mode=block_mode,
        block_row_counts=block_row_counts,
        hbm_budget_bytes=hbm_budget_bytes,
        trace_error=err,
        mesh=mesh,
        shardings=shardings,
    )
    diags = run_rules(ctx, codes=rules)
    return DiagnosticReport(
        diags,
        subject=subject or f"Program(inputs={[s.name for s in program.inputs]})",
    )


def analyze_frame(
    frame,
    fetches,
    block: bool = True,
    feed_dict=None,
    reduce_mode: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    probe: int = 8,
    hbm_budget_bytes: Optional[int] = None,
) -> DiagnosticReport:
    """Lint fetches *as a verb would run them* against ``frame``.

    The fetches normalize through the verbs' own path (DSL nodes /
    plain functions / Programs, feed_dict renames, x64 demotion), then
    lint with frame context: block-shape distribution feeds the
    TFG101 storm check **only when the frame is already materialized**
    — analysis never forces a lazy frame's pending computation. A
    sharded frame lints under its mesh context (normalization and the
    TFG108 stability probes alike): programs using sharding
    constraints/collectives analyze exactly as the executor traces
    them, with zero device transfers.
    """
    from ..ops.verbs import _apply_feed_dict, _normalize_program
    from ..parallel._shard_map import mesh_context

    with mesh_context(frame.mesh if frame.is_sharded else None):
        program, _ = _normalize_program(
            fetches, frame.schema, block=block, reduce_mode=reduce_mode,
            feed_dict=feed_dict,
        )
    program = _apply_feed_dict(program, feed_dict)
    counts: Optional[Tuple[int, ...]] = None
    if frame.is_materialized:
        from ..frame import _block_num_rows

        counts = tuple(_block_num_rows(b) for b in frame.blocks())
    mesh = None
    shardings = None
    if frame.is_sharded:
        # lint the program as its sharded dispatches will key: the
        # executor feeds each device column as a global array under the
        # frame's batch sharding, and those layout axes are part of the
        # persistent-store fingerprint (ISSUE 10)
        from ..config import get_config
        from ..parallel.mesh import batch_sharding

        mesh = frame.mesh
        axis = getattr(frame, "_axis", None) or get_config().batch_axis
        shardings = {}
        for spec in program.inputs:
            try:
                col = frame.schema[spec.name]
                if not col.is_device:
                    continue
            except KeyError:
                # not a frame column (feed_dict host array): the real
                # dispatch feeds it placement-free — probing it sharded
                # would fingerprint a key the executor never computes
                continue
            rank = max(1, len(spec.shape.dims))
            shardings[spec.name] = batch_sharding(mesh, rank, axis)
    return lint_program(
        program,
        probe=probe,
        rules=rules,
        block_mode=block,
        block_row_counts=counts,
        hbm_budget_bytes=hbm_budget_bytes,
        subject=f"fetches×frame({', '.join(frame.schema.names)})",
        mesh=mesh,
        shardings=shardings,
    )


def lint_plan(frame) -> DiagnosticReport:
    """Lint a frame's *logical plan* (TFG107 fusion-barrier, TFG109
    unfused-aggregate, TFG110 missed-aggregate-pushdown, TFG111
    larger-than-budget materialization, TFG112 liftable-callback /
    lift-declined, TFG113 prefix-cache-ineligible, TFG114
    query-not-incremental): warn when a
    chain's otherwise-fusable map stages are split by a barrier — a
    host-callback stage, a ``to_host``/``to_numpy`` materialization or
    repartition between maps, a trim map, or ragged source cells —
    when an aggregate/join consuming the chain stayed a barrier for a
    fixable reason (non-algebraic fetches, a group key computed by a
    chained stage, ragged value cells), and when an aggregate sits
    above a join it could push below but for a fixable cause (an
    order-sensitive float fetch, group keys not covering the join key,
    mixed-side fetches, an outer join, duplicate build keys), and when
    a forced ``to_host``/``to_numpy`` materialized an estimated byte
    volume past the block-store budget (the fix names the streaming
    out-of-core alternative, docs/dataplane.md). Each
    finding's ``explain()`` names the cause. Purely static over the
    recorded plan chain — never forces a lazy frame."""
    from ..plan.ir import chain_barriers, resolve_chain, unfused_epilogues
    from ..plan.lower import oversized_materializations, pushdown_misses

    n_maps, barriers = chain_barriers(frame)
    # verified-lift decisions (TFG112): each numpy UDF stage carries its
    # capture record — lifted (barrier cleared) or declined (reason +
    # offending AST node) — on the program plan/lift built
    lift_events = []
    node = getattr(frame, "_plan", None)
    if node is not None:
        _, nodes = resolve_chain(node)
        for n in nodes:
            info = getattr(getattr(n, "program", None),
                           "_tftpu_lift_info", None)
            if info:
                lift_events.append(dict(info))
    # serving evidence (TFG113): decode engines record when prompt
    # prefill work could not ride the prefix cache; import-guarded —
    # linting must work in a build without the serving extra
    try:
        from ..serving.decode import prefix_cache_events

        prefix_events = prefix_cache_events()
    except Exception:  # pragma: no cover - serving unavailable
        prefix_events = []
    # serving evidence (TFG114): registered query endpoints record when
    # their plan blocked result caching / incremental refresh; same
    # import guard as TFG113
    try:
        from ..serving.query import query_cache_events

        query_events = query_cache_events()
    except Exception:  # pragma: no cover - serving unavailable
        query_events = []
    ctx = RuleContext(
        program=None,
        plan_barriers=barriers,
        unfused_epilogues=unfused_epilogues(frame),
        pushdown_misses=pushdown_misses(frame),
        oversized_materializations=oversized_materializations(frame),
        lift_events=lift_events,
        prefix_cache_events=prefix_events,
        query_cache_events=query_events,
    )
    diags = run_rules(
        ctx,
        codes=["TFG107", "TFG109", "TFG110", "TFG111", "TFG112",
               "TFG113", "TFG114"],
    )
    return DiagnosticReport(
        diags, subject=f"plan({n_maps} map stage(s))"
    )
