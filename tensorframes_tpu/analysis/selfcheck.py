"""Repo self-lint: AST rules for hazards ruff has no opinion on.

Run: ``python -m tensorframes_tpu.analysis selfcheck [paths]``
(default: the ``tensorframes_tpu/`` package). Exit 0 when clean; 1 with
one ``path:line: TFL### message`` per finding. CI runs this in the
``lint`` job next to ruff and the program analyzer — one lint entry
point, the same split as the runtime: ruff = syntax/style, selfcheck =
repo conventions, ``tensorframes_tpu.analysis`` = user programs. The
TFL codes are registered in :mod:`.diagnostics`' catalog so
``explain()``-style tooling can resolve them; findings here print as
plain lint lines (they describe repo source, not a traced program).
``dev/lint_rules.py`` remains as a thin shim for muscle memory.

Rules (pragmas silence a single line):

* **TFL001** — bare ``jax.jit`` in library code outside the allowlisted
  modules. ``jax.jit(fn)`` embeds closure-captured weights as HLO
  literals and XLA constant-folds through them (measured round 3: int8
  weights re-materialized as f32, zero byte saving); new code must go
  through the hoisted path (``program.HoistedProgram`` /
  ``CompiledProgram``) or be explicitly allowlisted here with a reason.
  Pragma: ``# lint: allow-jax-jit``.
* **TFL002** — module-level mutable container mutated from function
  scope without a module-level ``threading.Lock``/``RLock`` (verbs run
  from prefetch worker threads; unsynchronized module state is a data
  race). Pragma: ``# lint: guarded``.
* **TFL003** — get-or-create metrics calls (``counter``/``gauge``/
  ``histogram`` on the default registry) inside a function. Instruments
  must pre-register at import so expositions always carry the full
  catalog (a counter that never fired reads 0, it does not vanish).
  Calls on an explicit registry object stay allowed. Pragma:
  ``# lint: runtime-metric-ok``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

REPO = Path(__file__).resolve().parent.parent.parent

# Modules where bare jax.jit is the implementation of (or deliberately
# adjacent to) the hoisted path itself, with the justification on record:
ALLOW_JAX_JIT = {
    "tensorframes_tpu/program.py",         # HoistedProgram IS the hoisted path
    "tensorframes_tpu/ops/executor.py",    # CompiledProgram entrypoints
    "tensorframes_tpu/ops/verbs.py",       # seg fast path / sharded folds: no closure weights
    "tensorframes_tpu/ops/device_agg.py",  # shard_map plans over runtime args
    "tensorframes_tpu/ops/exchange.py",    # collective shuffles, no weights
    "tensorframes_tpu/ops/attention.py",   # pallas kernel wrappers
    "tensorframes_tpu/ops/quantize.py",    # kernel micro-entry, args only
    "tensorframes_tpu/frame.py",           # relational masks over runtime args
    "tensorframes_tpu/parallel/pipeline.py",  # per-stage shard_map programs
    "tensorframes_tpu/models/moe.py",      # params passed as arguments
    "tensorframes_tpu/models/transformer.py",  # params passed as arguments
    "tensorframes_tpu/training.py",        # step fns take params as args
    "tensorframes_tpu/plan/lift.py",       # verify jit: synthesized fn, no closure weights
}

MUTATORS = {
    "append", "add", "update", "setdefault", "pop", "clear", "extend",
    "insert", "remove", "popitem", "discard",
}

METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _pragma(lines: List[str], lineno: int, tag: str) -> bool:
    line = lines[lineno - 1] if 0 < lineno <= len(lines) else ""
    return f"lint: {tag}" in line


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("dict", "list", "set", "deque", "defaultdict")
    return False


def _creates_lock(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("Lock", "RLock"):
                return True
            if isinstance(f, ast.Name) and f.id in ("Lock", "RLock"):
                return True
    return False


def _jax_jit_findings(tree, rel, lines) -> List[Tuple[int, str, str]]:
    out = []
    jit_aliases = {"jit"} if any(
        isinstance(n, ast.ImportFrom) and n.module == "jax"
        and any(a.name == "jit" for a in n.names)
        for n in ast.walk(tree)
    ) else set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (
            isinstance(f, ast.Attribute) and f.attr == "jit"
            and isinstance(f.value, ast.Name) and f.value.id == "jax"
        ) or (isinstance(f, ast.Name) and f.id in jit_aliases)
        if not hit:
            continue
        if rel in ALLOW_JAX_JIT or _pragma(lines, node.lineno, "allow-jax-jit"):
            continue
        out.append((
            node.lineno, "TFL001",
            "bare jax.jit in library code: closure constants fold into the "
            "HLO (un-doing int8, bloating per-shape compiles) — use the "
            "hoisted path (program.HoistedProgram / CompiledProgram) or "
            "allowlist the module in analysis/selfcheck.py with a reason",
        ))
    return out


def _mutable_state_findings(tree, rel, lines) -> List[Tuple[int, str, str]]:
    module_containers = {}
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target = node.target.id
            value = node.value
        if target is None or not _is_mutable_literal(value):
            continue
        if _pragma(lines, node.lineno, "guarded"):
            continue
        module_containers[target] = node.lineno
    if not module_containers:
        return []
    has_lock = _creates_lock(tree)

    mutated = set()

    class FnVisitor(ast.NodeVisitor):
        def __init__(self):
            self.depth = 0

        def visit_FunctionDef(self, node):
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        visit_AsyncFunctionDef = visit_FunctionDef

        def _name_of(self, v):
            return v.id if isinstance(v, ast.Name) else None

        def visit_Call(self, node):
            if self.depth and isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS:
                n = self._name_of(node.func.value)
                if n in module_containers:
                    mutated.add(n)
            self.generic_visit(node)

        def visit_Subscript(self, node):
            if self.depth and isinstance(node.ctx, (ast.Store, ast.Del)):
                n = self._name_of(node.value)
                if n in module_containers:
                    mutated.add(n)
            self.generic_visit(node)

    FnVisitor().visit(tree)
    out = []
    if not has_lock:
        for name in sorted(mutated):
            out.append((
                module_containers[name], "TFL002",
                f"module-level mutable {name!r} is mutated from function "
                "scope but the module creates no threading.Lock/RLock — "
                "guard it (or mark the line '# lint: guarded' with a "
                "single-threaded justification)",
            ))
    return out


def _metric_findings(tree, rel, lines) -> List[Tuple[int, str, str]]:
    # alias map: imported-from observability.metrics names → factory kind
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.endswith("observability.metrics"):
            for a in node.names:
                if a.name in METRIC_FACTORIES:
                    aliases[a.asname or a.name] = a.name
    if not aliases:
        return []
    out = []

    class FnVisitor(ast.NodeVisitor):
        def __init__(self):
            self.depth = 0

        def visit_FunctionDef(self, node):
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            f = node.func
            bad = False
            if isinstance(f, ast.Name) and f.id in aliases:
                bad = self.depth > 0
            elif isinstance(f, ast.Attribute) and f.attr in METRIC_FACTORIES \
                    and isinstance(f.value, ast.Name) and f.value.id == "REGISTRY":
                bad = self.depth > 0
            if bad and not _pragma(lines, node.lineno, "runtime-metric-ok"):
                out.append((
                    node.lineno, "TFL003",
                    "metrics get-or-create inside a function: instruments "
                    "must pre-register at import so the exposition always "
                    "carries the full catalog (move to module level, pass "
                    "an explicit registry, or mark "
                    "'# lint: runtime-metric-ok')",
                ))
            self.generic_visit(node)

    FnVisitor().visit(tree)
    return out


def lint_file(path: Path) -> List[str]:
    rel = str(path.relative_to(REPO)) if path.is_relative_to(REPO) else str(path)
    src = path.read_text()
    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: TFL000 syntax error: {e.msg}"]
    findings = []
    findings += _jax_jit_findings(tree, rel, lines)
    findings += _mutable_state_findings(tree, rel, lines)
    findings += _metric_findings(tree, rel, lines)
    return [f"{rel}:{ln}: {code} {msg}" for ln, code, msg in sorted(findings)]


def main(argv: List[str]) -> int:
    roots = [Path(a) for a in argv] or [REPO / "tensorframes_tpu"]
    files: List[Path] = []
    for r in roots:
        files.extend(sorted(r.rglob("*.py")) if r.is_dir() else [r])
    all_findings: List[str] = []
    for f in files:
        all_findings.extend(lint_file(f))
    for line in all_findings:
        print(line)
    print(
        f"analysis selfcheck: {len(files)} file(s), "
        f"{len(all_findings)} finding(s)"
    )
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
