"""Entry point: ``python -m tensorframes_tpu.analysis``."""

import sys

from .cli import main

sys.exit(main())
