"""Fused paged int8-KV decode attention (the kernel half of the
serving decode engine, ROADMAP #6 / ISSUE 12).

The XLA lowering of ``models/generation.paged_decode_step_fn`` runs
decode attention as a chain: gather every slot's pages into a
materialized ``[S, pages, heads, page, hd]`` HBM copy, dequantize, and
attend. Decode is HBM-bandwidth-bound, so that copy IS the cost. This
kernel fuses the chain: the grid walks ``(slot, page-table entry)``,
each page streams HBM→VMEM **as int8** through a scalar-prefetched
page-table index map (the vLLM paged-attention shape), scales ride
along, and on a slot's last page the whole attention — dequantize,
scores, null/validity masking, softmax, context — runs in-register.
Nothing gathered ever touches HBM.

Bit-identity: the kernel performs the REFERENCE chain's exact op
sequence per slot (same einsums, same ``preferred_element_type``, same
masking constant, same softmax) — on the CPU pallas interpreter the
output is bit-identical to the XLA chain (asserted in tests), and the
engine-level gates (batched==solo, preemption replay, the dense
``generate()`` oracle) hold whichever lowering the cost model picks
because the choice is made once per engine, not per step.

Null-page handling is inherited unchanged: padding slots carry
all-null tables (every gathered page is page 0) and real slots mask to
``position <= pos``, so the null page's garbage never reaches an
unmasked score — the same invariant the XLA chain relies on.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def paged_decode_attention(
    q: jnp.ndarray,          # [S, nh, hd] activation dtype
    k_pages: jnp.ndarray,    # [P, L, nh, page, hd] int8
    v_pages: jnp.ndarray,    # [P, L, nh, page, hd] int8
    k_scale: jnp.ndarray,    # [P, L, nh, page, 1] f32
    v_scale: jnp.ndarray,    # [P, L, nh, page, 1] f32
    layer: int,              # static layer index
    tables: jnp.ndarray,     # [S, maxp] int32 page tables
    pos: jnp.ndarray,        # [S] int32 current positions
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """One layer's paged decode attention for every slot: returns the
    ``[S, nh, hd]`` context in ``q.dtype``. Traceable (callers embed it
    in the jitted decode step); ``interpret`` defaults to the backend's
    :func:`tensorframes_tpu.kernels.interpret_mode`."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from . import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    S, nh, hd = q.shape
    page = int(k_pages.shape[3])
    maxp = int(tables.shape[1])
    C = maxp * page
    dtype = q.dtype
    li = int(layer)

    def kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
               o_ref, k8, v8, ks, vs):
        s = pl.program_id(0)
        j = pl.program_id(1)
        sl = pl.ds(j * page, page)
        k8[:, sl, :] = k_ref[0, 0]
        v8[:, sl, :] = v_ref[0, 0]
        ks[:, sl] = ks_ref[0, 0, :, :, 0]
        vs[:, sl] = vs_ref[0, 0, :, :, 0]

        @pl.when(j == maxp - 1)
        def _attend():
            neg = jnp.asarray(-1e30, jnp.float32)
            # [1, C] validity row — broadcasting over heads exactly as
            # the reference's valid[:, None, :] slice does per slot
            valid = lax.broadcasted_iota(
                jnp.int32, (1, C), 1
            ) <= pos_ref[s]
            scores = jnp.einsum(
                "hd,hcd->hc", q_ref[0], k8[:].astype(dtype),
                preferred_element_type=jnp.float32,
            ) / float(np.sqrt(hd))
            scores = scores * ks[:]
            scores = jnp.where(valid, scores, neg)
            w = jax.nn.softmax(scores, axis=-1)
            w = (w * vs[:]).astype(dtype)
            o_ref[0] = jnp.einsum("hc,hcd->hd", w, v8[:].astype(dtype))

    # Every index-map component derives from a grid index (``j - j``
    # zeros): this package enables x64 at import, under which literal
    # ints trace i64 beside the i32 grid index and Mosaic fails to
    # legalize the mixed-type func.return (the ops/segment.py lesson).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, maxp),
        in_specs=[
            pl.BlockSpec(
                (1, nh, hd), lambda s, j, tbl, p: (s, j - j, j - j)
            ),
            pl.BlockSpec(
                (1, 1, nh, page, hd),
                lambda s, j, tbl, p: (
                    tbl[s, j], (j - j) + li, j - j, j - j, j - j
                ),
            ),
            pl.BlockSpec(
                (1, 1, nh, page, hd),
                lambda s, j, tbl, p: (
                    tbl[s, j], (j - j) + li, j - j, j - j, j - j
                ),
            ),
            pl.BlockSpec(
                (1, 1, nh, page, 1),
                lambda s, j, tbl, p: (
                    tbl[s, j], (j - j) + li, j - j, j - j, j - j
                ),
            ),
            pl.BlockSpec(
                (1, 1, nh, page, 1),
                lambda s, j, tbl, p: (
                    tbl[s, j], (j - j) + li, j - j, j - j, j - j
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, nh, hd), lambda s, j, tbl, p: (s, j - j, j - j)
        ),
        scratch_shapes=[
            pltpu.VMEM((nh, C, hd), jnp.int8),
            pltpu.VMEM((nh, C, hd), jnp.int8),
            pltpu.VMEM((nh, C), jnp.float32),
            pltpu.VMEM((nh, C), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nh, hd), dtype),
        interpret=bool(interpret),
    )(
        tables.astype(jnp.int32), pos.astype(jnp.int32),
        q, k_pages, v_pages, k_scale, v_scale,
    )


def paged_attention_reference(
    q, k_pages, v_pages, k_scale, v_scale, layer, tables, pos
):
    """The XLA gather→dequant→attend chain — this IS the production
    lowering (``paged_decode_step_fn``'s non-kernel branch calls it)
    AND the oracle the kernel is bit-identity-gated against, so the
    two can never drift apart."""
    S, nh, hd = q.shape
    page = int(k_pages.shape[3])
    maxp = int(tables.shape[1])
    C = maxp * page
    dtype = q.dtype
    li = int(layer)
    neg = jnp.asarray(-1e30, jnp.float32)
    valid = jnp.arange(C)[None, :] <= pos[:, None]
    pk = k_pages[tables, li]
    pv = v_pages[tables, li]
    pks = k_scale[tables, li][..., 0]
    pvs = v_scale[tables, li][..., 0]
    pk = pk.transpose(0, 2, 1, 3, 4).reshape(S, nh, C, hd)
    pv = pv.transpose(0, 2, 1, 3, 4).reshape(S, nh, C, hd)
    pks = pks.transpose(0, 2, 1, 3).reshape(S, nh, C)
    pvs = pvs.transpose(0, 2, 1, 3).reshape(S, nh, C)
    scores = jnp.einsum(
        "nhd,nhcd->nhc", q, pk.astype(dtype),
        preferred_element_type=jnp.float32,
    ) / float(np.sqrt(hd))
    scores = scores * pks
    scores = jnp.where(valid[:, None, :], scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    w = (w * pvs).astype(dtype)
    return jnp.einsum("nhc,nhcd->nhd", w, pv.astype(dtype))
