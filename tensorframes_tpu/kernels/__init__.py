"""Pallas straggler kernels, selected per segment by the plan cost model.

The bench trajectory names three hot paths the default XLA lowerings
leave on the table (ROADMAP #6): ragged ``map_rows`` (~12M rows/s vs
1B+ for fixed-shape add3), decode attention (~17k tokens/s at 512 seq —
the steady-state inner loop of the serving decode engine), and the
segment reduce PR 7 routed to a host ``np.bincount`` because XLA:CPU
serializes scatter. This package holds the purpose-built kernels:

* :mod:`.segment_reduce` — one fused pallas dispatch computing every
  (column, op) of a keyed reduction: sum/mean via the one-hot MXU
  contraction, min/max via masked VPU reductions, sorted-or-not ids.
* :mod:`.decode_attention` — paged int8-KV decode attention: per-slot
  pages stream HBM→VMEM through the page table (scalar-prefetch index
  maps), dequantize in-register, and the attention math runs in the
  same kernel — the gather→dequant→attend chain of
  ``models/generation.paged_decode_step_fn`` becomes ONE kernel with no
  materialized ``[S, pages, heads, page, hd]`` copy.
* :mod:`.ragged_gather` — ragged row staging on device: cells move as
  one flat buffer + offsets, and the kernel scatters each shape
  group's rows into its padded batch in VMEM, replacing the per-group
  host ``np.stack`` + transfer of the ragged ``map_rows`` path.

**Selection is a counted cost-model decision** (``plan/rules.py``:
``decide_segment_reduce`` / ``decide_decode_attention`` /
``decide_ragged_gather`` → ``pallas_*`` decision values), never an
unconditional dispatch: kernels engage on TPU-family backends (or
everywhere under ``TFTPU_PALLAS_FORCE=1``, which tests and the
in-bench bit-identity gates use — the CPU pallas interpreter runs the
kernels there, so tier-1 stays green under ``JAX_PLATFORMS=cpu``).
``TFTPU_PALLAS=0`` removes them from every decision, and the runtime
Mosaic kill-switch (:func:`tensorframes_tpu.ops.segment.disable_pallas`)
covers recovery — it already invalidates the fused-program cache, and
:func:`enabled` consults it, so a tripped switch disables THIS package
too and no stale executable survives (the compile-cache fingerprint
carries :func:`fingerprint_token`).

Every kernel is **bit-identity-gated**: against its plain-jnp
same-tiling reference emulation always (exact by construction — the
gate that catches indexing/masking/dequant bugs), and against the
XLA/host reference wherever exactness is structural (min/max, integer
sums, and the decode-attention chain, which the pallas interpreter
reproduces bit-for-bit on CPU).
"""

from __future__ import annotations

import time
from typing import Dict

from ..observability.metrics import counter as _counter
from ..observability.metrics import histogram as _histogram

__all__ = [
    "KERNELS",
    "enabled",
    "force_active",
    "interpret_mode",
    "fingerprint_token",
    "note_dispatch",
    "build_timer",
]

#: The registered kernel names — one counted dispatch series each, and
#: the vocabulary of the ``pallas_*`` cost-model decision values.
KERNELS = ("segment_reduce", "decode_attn", "ragged_gather")

# Pre-registered at import (the `# kernels |` bench summary and the
# exposition must always carry the family — a process that never
# dispatched a kernel reads 0, the series does not vanish).
DISPATCHES = {
    k: _counter(
        "tftpu_kernels_dispatch_total",
        "Pallas straggler-kernel dispatches, by kernel",
        labels={"kernel": k},
    )
    for k in KERNELS
}
INTERPRET_FALLBACKS = {
    k: _counter(
        "tftpu_kernels_interpret_fallback_total",
        "Kernel dispatches that ran on the CPU pallas interpreter "
        "instead of a compiled Mosaic kernel, by kernel",
        labels={"kernel": k},
    )
    for k in KERNELS
}
BUILD_SECONDS = _histogram(
    "tftpu_kernels_build_seconds",
    "Wall-clock of building (tracing + first-dispatch compiling) one "
    "straggler-kernel call",
)


def enabled() -> bool:
    """True when the straggler kernels may be selected at all: the
    ``TFTPU_PALLAS`` config switch is on AND the process-wide Mosaic
    kill-switch has not tripped (``ops.segment.disable_pallas`` — one
    switch covers every pallas family, and tripping it already clears
    the fused-program cache so no stale trace replays)."""
    from ..config import get_config
    from ..ops import segment as _segment

    return bool(get_config().pallas_kernels) and _segment.pallas_enabled()


def force_active() -> bool:
    """``TFTPU_PALLAS_FORCE`` — select kernels even off-TPU (the pallas
    interpreter runs them). The bit-identity test/bench hook."""
    from ..config import get_config

    return bool(get_config().pallas_force)


def interpret_mode() -> bool:
    """True when kernels must run on the pallas CPU interpreter (no
    Mosaic toolchain for this backend) — the tier-1 configuration."""
    import jax

    return jax.default_backend() not in ("tpu", "axon")


def fingerprint_token() -> Dict[str, object]:
    """The kernel-selection state that must key every compiled
    executable (folded into the compile-cache fingerprint's env slot):
    a ``disable_pallas()`` flip, a ``TFTPU_PALLAS``/``_FORCE`` change,
    or moving between interpreter and Mosaic must all miss cleanly —
    a store hit across any of them would replay a stale lowering."""
    return {
        "enabled": enabled(),
        "force": force_active(),
        "interpret": interpret_mode(),
    }


def note_dispatch(kernel: str, interpret: bool) -> None:
    """Count one kernel dispatch (and its interpreter fallback)."""
    DISPATCHES[kernel].inc()
    if interpret:
        INTERPRET_FALLBACKS[kernel].inc()


class build_timer:
    """``with build_timer(): ...`` — records kernel build wall-clock."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        BUILD_SECONDS.observe(time.perf_counter() - self._t0)
        return False
