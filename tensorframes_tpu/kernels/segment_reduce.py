"""Fused multi-op pallas segment reduce — the third keyed-reduction
strategy beside the jitted scatter and the host ``np.bincount``
(``ops/segment.py``), selected per segment by
``plan/rules.decide_segment_reduce``.

One pallas dispatch computes EVERY (column, op) fetch of a keyed
``aggregate``: the grid walks row tiles sequentially and accumulates
per-segment partials into the same output block —

* ``sum``/``mean`` of floats: the one-hot MXU contraction (the PR 7
  trick — ``[tile, segments]`` membership one-hot against the value
  tile as a dense f32 matmul, ``precision=HIGHEST``);
* ``sum``/``mean`` of ints/bools: the same one-hot contraction with an
  **int32 accumulator** (``preferred_element_type=int32`` — exact
  associative arithmetic, bit-identical to the scatter by
  construction);
* ``min``/``max``: a masked VPU reduction over the
  ``[tile, segments, d]`` broadcast (order-free, so also exactly the
  scatter's bits); the row tile shrinks adaptively so that broadcast
  stays VMEM-bounded, and :func:`eligible` refuses shapes where it
  cannot.

Mean division and final dtype casts happen OUTSIDE the kernel with the
jitted path's formula (``(s / c).astype(v.dtype)``; the count table is
i32-exact). Bit-identity is gated two ways: against
:func:`segment_reduce_reference` — the same tiled computation in plain
jnp, exact by construction for every op/dtype — and against the XLA
scatter for the order-free classes (min/max, integer sums).

Sorted-or-not segment ids; padded rows carry id ``num_segments`` and
match nothing real (a padded row can land in a padded SEGMENT slot,
which the final slice discards). Runs on the pallas CPU interpreter
when no Mosaic toolchain serves the backend
(:func:`tensorframes_tpu.kernels.interpret_mode`).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import build_timer, note_dispatch

#: default rows per grid step (sublane-aligned); shrinks for min/max
_TILE_ROWS = 256
#: past this, the one-hot wastes more FLOPs than the scatter costs
MAX_SEGMENTS = 4096
#: element budget for the [tile, segments, d] min/max broadcast
_MASK_BUDGET = 1 << 20

_FLOAT_OK = ("float32", "bfloat16")
_INT_OK = ("int32", "int16", "int8", "uint8", "bool")
_OPS = ("reduce_sum", "reduce_mean", "reduce_min", "reduce_max")


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _dtype_name(v) -> str:
    return str(v.dtype)


def _np_to_jnp_dtype(name: str):
    return jnp.bfloat16 if name == "bfloat16" else np.dtype(name)


def _col_meta(ops_key, val_cols) -> Tuple[Tuple[str, str, int, int, str], ...]:
    """Per-column (name, dtype, inner dim, ndim, op) — the build-cache
    key axis that varies with the feed."""
    meta = []
    for x, op in ops_key:
        v = val_cols[x]
        ndim = int(getattr(v, "ndim", 1))
        d = 1 if ndim == 1 else int(v.shape[1])
        meta.append((x, _dtype_name(v), d, ndim, op))
    return tuple(meta)


def _tile_rows(meta, num_segments: int) -> int:
    """Row-tile size: the default unless a min/max column's masked
    broadcast would blow the element budget, in which case shrink
    (never below the 8-row sublane floor — :func:`eligible` refuses
    shapes that would still not fit there)."""
    s_pad = _round_up(max(num_segments, 1), 8)
    tile = _TILE_ROWS
    for _, _, d, _, op in meta:
        if op in ("reduce_min", "reduce_max"):
            d_pad = _round_up(d, 128)
            while tile > 8 and tile * s_pad * d_pad > _MASK_BUDGET:
                tile //= 2
    return tile


def eligible(ops_key, val_cols, num_segments: int) -> bool:
    """True when the fused pallas kernel can serve this keyed
    reduction exactly: bounded segment count, 1-D/2-D values, float32/
    bfloat16 (f32 accumulate) or ≤32-bit int/bool (i32 accumulate —
    wider ints could overflow the exact accumulator), and a min/max
    broadcast that fits the tile budget."""
    if not 0 < num_segments <= MAX_SEGMENTS:
        return False
    for x, op in ops_key:
        if op not in _OPS:
            return False
        v = val_cols[x]
        if getattr(v, "ndim", None) not in (1, 2):
            return False
        if _dtype_name(v) not in _FLOAT_OK + _INT_OK:
            return False
    meta = _col_meta(ops_key, val_cols)
    tile = _tile_rows(meta, num_segments)
    s_pad = _round_up(num_segments, 8)
    return not any(
        tile * s_pad * _round_up(d, 128) > _MASK_BUDGET
        for _, _, d, _, op in meta
        if op in ("reduce_min", "reduce_max")
    )


def _acc_dtype(dtype_name: str):
    """(accumulator dtype, is_float) for a sum/mean column."""
    if dtype_name in _FLOAT_OK:
        return jnp.float32, True
    return jnp.int32, False


def _minmax_identity(dtype_name: str, op: str):
    if dtype_name in _FLOAT_OK:
        return jnp.asarray(
            jnp.inf if op == "reduce_min" else -jnp.inf,
            _np_to_jnp_dtype(dtype_name),
        )
    if dtype_name == "bool":
        return jnp.asarray(op == "reduce_min", jnp.bool_)
    info = np.iinfo(np.dtype(dtype_name))
    return jnp.asarray(
        info.max if op == "reduce_min" else info.min,
        np.dtype(dtype_name),
    )


def _tile_partial(op: str, dtype_name: str, seg: jnp.ndarray,
                  vals: jnp.ndarray, s_pad: int):
    """One tile's per-segment partial — THE shared math of the kernel
    body and the plain-jnp reference emulation (bit-identity between
    them is by construction: same ops, same order, same dtypes).
    ``seg`` [tile] int32, ``vals`` [tile, d_pad]."""
    tile = seg.shape[0]
    seg_iota = lax.broadcasted_iota(jnp.int32, (tile, s_pad), 1)
    member = seg[:, None] == seg_iota                      # [tile, s_pad]
    if op in ("reduce_sum", "reduce_mean"):
        acc, is_float = _acc_dtype(dtype_name)
        kw = {"precision": lax.Precision.HIGHEST} if is_float else {}
        return lax.dot_general(
            member.astype(acc),
            vals.astype(acc),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc,
            **kw,
        )
    ident = _minmax_identity(dtype_name, op)
    masked = jnp.where(member[:, :, None], vals[:, None, :], ident)
    red = jnp.min if op == "reduce_min" else jnp.max
    return red(masked, axis=0)                             # [s_pad, d_pad]


def _count_partial(seg: jnp.ndarray, s_pad: int) -> jnp.ndarray:
    """Per-segment row counts for one tile (i32-exact; every lane of
    the [s_pad, 128] table carries the same count — lane 0 is read)."""
    tile = seg.shape[0]
    seg_iota = lax.broadcasted_iota(jnp.int32, (tile, s_pad), 1)
    member = (seg[:, None] == seg_iota).astype(jnp.int32)
    return lax.dot_general(
        member,
        jnp.ones((tile, 128), jnp.int32),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _pad_inputs(meta, num_segments, val_cols, seg_ids, tile):
    """Tile-pad the feed: segs [n_pad, 1] (padding rows → id ==
    num_segments), each column [n_pad, d_pad]."""
    seg_ids = jnp.asarray(np.asarray(seg_ids)).astype(jnp.int32)
    n = int(seg_ids.shape[0])
    n_pad = _round_up(max(n, 1), tile)
    segs = jnp.full((n_pad, 1), num_segments, jnp.int32)
    if n:
        segs = segs.at[:n, 0].set(seg_ids)
    padded = {}
    for x, dtype_name, d, ndim, _ in meta:
        v = jnp.asarray(val_cols[x])
        v2 = v[:, None] if ndim == 1 else v
        d_pad = _round_up(d, 128)
        buf = jnp.zeros((n_pad, d_pad), v2.dtype)
        if n:
            buf = buf.at[:n, :d].set(v2)
        padded[x] = buf
    return segs, padded, n_pad


def _finalize(meta, num_segments, partials, counts):
    """Slice away padding and apply the jitted path's mean/cast
    formula: ``s.astype(v.dtype)`` for sums, ``(s / c).astype(v.dtype)``
    for means. Returns 2-D [K, d] columns (callers restore 1-D)."""
    out = {}
    for x, dtype_name, d, _, op in meta:
        dt = _np_to_jnp_dtype(dtype_name)
        p = partials[x][:num_segments, :d]
        if op in ("reduce_min", "reduce_max"):
            out[x] = p
        elif op == "reduce_sum":
            out[x] = p.astype(dt)
        else:  # reduce_mean
            s = p.astype(dt)
            c = counts[:num_segments, :1].astype(dt)
            out[x] = (s / c).astype(dt)
    return out


def _unpad(meta, res) -> Dict[str, np.ndarray]:
    out = {}
    for x, _, _, ndim, _ in meta:
        v = np.asarray(res[x])
        out[x] = v[:, 0] if ndim == 1 else v
    return out


@lru_cache(maxsize=32)
def _pallas_fn_for(meta, num_segments: int, interpret: bool):
    """Build (once per op-set/shape family) the jitted wrapper whose
    body is ONE pallas_call computing every partial + the shared count
    table. ``meta`` is the :func:`_col_meta` tuple."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tile = _tile_rows(meta, num_segments)
    s_pad = _round_up(num_segments, 8)
    need_counts = any(op == "reduce_mean" for *_, op in meta)
    n_cols = len(meta)

    def kernel(seg_ref, *refs):
        val_refs = refs[:n_cols]
        out_refs = refs[n_cols:2 * n_cols]
        cnt_ref = refs[2 * n_cols] if need_counts else None
        first = pl.program_id(0) == 0
        seg = seg_ref[:, 0]
        for (x, dtype_name, d, ndim, op), v_ref, o_ref in zip(
            meta, val_refs, out_refs
        ):
            part = _tile_partial(op, dtype_name, seg, v_ref[:], s_pad)
            if op in ("reduce_min", "reduce_max"):
                ident = _minmax_identity(dtype_name, op)

                @pl.when(first)
                def _init(o_ref=o_ref, ident=ident):
                    o_ref[:] = jnp.full(
                        o_ref.shape, ident, o_ref.dtype
                    )

                comb = jnp.minimum if op == "reduce_min" else jnp.maximum
                o_ref[:] = comb(o_ref[:], part)
            else:
                @pl.when(first)
                def _init(o_ref=o_ref):
                    o_ref[:] = jnp.zeros_like(o_ref)

                o_ref[:] += part
        if cnt_ref is not None:
            @pl.when(first)
            def _init_c():
                cnt_ref[:] = jnp.zeros_like(cnt_ref)

            cnt_ref[:] += _count_partial(seg, s_pad)

    @jax.jit
    def run(segs, vals):
        n_pad = segs.shape[0]
        grid = (n_pad // tile,)
        # every index-map component derives from the grid index: this
        # package enables x64 at import, under which a literal 0
        # traces i64 beside the i32 grid index and Mosaic fails to
        # legalize the mixed-type func.return (the ops/segment.py
        # lesson); ``i - i`` is an i32 zero
        in_specs = [pl.BlockSpec((tile, 1), lambda i: (i, i - i),
                                 memory_space=pltpu.VMEM)]
        out_shapes = []
        out_specs = []
        ins = [segs]
        for x, dtype_name, d, ndim, op in meta:
            d_pad = _round_up(d, 128)
            in_specs.append(pl.BlockSpec(
                (tile, d_pad), lambda i: (i, i - i),
                memory_space=pltpu.VMEM,
            ))
            ins.append(vals[x])
            if op in ("reduce_min", "reduce_max"):
                out_dt = _np_to_jnp_dtype(dtype_name)
            else:
                out_dt = _acc_dtype(dtype_name)[0]
            out_shapes.append(jax.ShapeDtypeStruct((s_pad, d_pad), out_dt))
            out_specs.append(pl.BlockSpec(
                (s_pad, d_pad), lambda i: (i - i, i - i),
                memory_space=pltpu.VMEM,
            ))
        if need_counts:
            out_shapes.append(
                jax.ShapeDtypeStruct((s_pad, 128), jnp.int32)
            )
            out_specs.append(pl.BlockSpec(
                (s_pad, 128), lambda i: (i - i, i - i),
                memory_space=pltpu.VMEM,
            ))
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(*ins)
        partials = {meta[k][0]: outs[k] for k in range(n_cols)}
        counts = outs[n_cols] if need_counts else None
        return _finalize(meta, num_segments, partials, counts)

    return run


def segment_reduce_pallas(
    ops_key, num_segments: int, val_cols, seg_ids,
    interpret: Optional[bool] = None,
) -> Dict[str, np.ndarray]:
    """Run the fused kernel: ``ops_key`` is the ((name, op), ...) tuple
    of ``_segment_reduce_best``, ``val_cols`` maps names to 1-D/2-D
    numpy or jax arrays, ``seg_ids`` the int row→segment map. Returns
    numpy columns sliced to ``num_segments``, dtypes matching the
    jitted path's contract. Caller gates :func:`eligible` first."""
    from . import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    meta = _col_meta(ops_key, val_cols)
    tile = _tile_rows(meta, num_segments)
    with build_timer():
        fn = _pallas_fn_for(meta, num_segments, bool(interpret))
    segs, padded, _ = _pad_inputs(
        meta, num_segments, val_cols, seg_ids, tile
    )
    note_dispatch("segment_reduce", bool(interpret))
    return _unpad(meta, fn(segs, padded))


def segment_reduce_reference(
    ops_key, num_segments: int, val_cols, seg_ids,
) -> Dict[str, np.ndarray]:
    """Plain-jnp emulation of the kernel's exact tiled computation —
    the bit-identity oracle (same per-tile math via
    :func:`_tile_partial`, same sequential tile order, same finalize
    formula; no pallas anywhere). Tests and the in-bench gate assert
    ``segment_reduce_pallas == segment_reduce_reference`` bitwise."""
    meta = _col_meta(ops_key, val_cols)
    tile = _tile_rows(meta, num_segments)
    s_pad = _round_up(num_segments, 8)
    segs, padded, n_pad = _pad_inputs(
        meta, num_segments, val_cols, seg_ids, tile
    )
    seg_flat = segs[:, 0]
    need_counts = any(op == "reduce_mean" for *_, op in meta)
    partials: Dict[str, jnp.ndarray] = {}
    counts = None
    for t in range(n_pad // tile):
        seg_t = seg_flat[t * tile:(t + 1) * tile]
        for x, dtype_name, d, ndim, op in meta:
            v_t = padded[x][t * tile:(t + 1) * tile]
            part = _tile_partial(op, dtype_name, seg_t, v_t, s_pad)
            if x not in partials:
                if op in ("reduce_min", "reduce_max"):
                    partials[x] = jnp.full(
                        part.shape, _minmax_identity(dtype_name, op),
                        part.dtype,
                    )
                else:
                    partials[x] = jnp.zeros_like(part)
            if op in ("reduce_min", "reduce_max"):
                comb = jnp.minimum if op == "reduce_min" else jnp.maximum
                partials[x] = comb(partials[x], part)
            else:
                partials[x] = partials[x] + part
        if need_counts:
            cp = _count_partial(seg_t, s_pad)
            counts = cp if counts is None else counts + cp
    return _unpad(
        meta, _finalize(meta, num_segments, partials, counts)
    )
