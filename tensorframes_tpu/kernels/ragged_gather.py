"""Pallas ragged row gather — device-side staging for ragged
``map_rows`` (the ~12M rows/s straggler vs 1B+ for fixed-shape add3).

The ragged fallback groups rows by cell shape, then per group
``np.stack``-s the cells on the HOST and ships the padded batch to the
device — for B shape groups that is B host stack passes and B
transfers, and the host stack dominated every measured round. With
this kernel the cells move ONCE, as a flat concatenation: the kernel's
grid walks the rows of one shape group, each row's slice streaming
from the flat buffer in HBM straight into its row of the padded VMEM
batch via a scalar-prefetched start offset (async DMA — no gathered
copy ever materializes on the host). The group's vmapped program then
runs on the device-resident batch.

Pure data movement: the gather is **bit-identical to the host
``np.stack`` staging by construction** (asserted in tests), so the
ragged ``map_rows`` results cannot change — only where the bytes flow.
Selected by ``plan/rules.decide_ragged_gather`` (counted
``pallas_ragged_gather``); the single-1-D-ragged-column fast path is
the eligible shape, mirroring the vectorized grouping fast path it
accelerates.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import build_timer, note_dispatch


@lru_cache(maxsize=64)
def _gather_fn_for(length: int, dtype_name: str, interpret: bool):
    """Jitted gather for one cell length: ``fn(flat [T], starts [g])
    -> [g, length]`` (re-traced per distinct g by jit, executable
    cached)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(starts_ref, flat_ref, o_ref, sem):
        r = pl.program_id(0)
        cp = pltpu.make_async_copy(
            flat_ref.at[pl.ds(starts_ref[r], length)],
            o_ref.at[0],
            sem,
        )
        cp.start()
        cp.wait()

    @jax.jit
    def run(flat, starts):
        g = starts.shape[0]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(g,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),  # flat stays HBM
            ],
            out_specs=pl.BlockSpec(
                (1, length), lambda r, starts: (r, r - r)
            ),
            scratch_shapes=[pltpu.SemaphoreType.DMA],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(
                (g, length), flat.dtype
            ),
            interpret=interpret,
        )(starts.astype(jnp.int32), flat)

    return run


def ragged_gather_rows(
    flat: jnp.ndarray,       # [T] the flat cell concatenation (device)
    starts,                  # [g] int32 start offsets into ``flat``
    length: int,             # the group's (uniform) cell length
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Gather ``g`` rows of ``length`` cells from ``flat`` into a dense
    device batch ``[g, length]``. ``starts`` may be numpy or device;
    rows may overlap (padding rows reuse offset 0)."""
    from . import interpret_mode

    if interpret is None:
        interpret = interpret_mode()
    if length < 1:
        raise ValueError(
            f"ragged_gather_rows needs length >= 1, got {length} "
            "(zero-length cells stay on the host stack path)"
        )
    with build_timer():
        fn = _gather_fn_for(
            int(length), str(flat.dtype), bool(interpret)
        )
    note_dispatch("ragged_gather", bool(interpret))
    return fn(flat, jnp.asarray(np.asarray(starts, dtype=np.int32)))


def gather_reference(flat, starts, length: int) -> np.ndarray:
    """Host emulation of the gather (the ``np.stack`` staging the
    kernel replaces) — the bit-identity oracle."""
    flat = np.asarray(flat)
    return np.stack([
        flat[int(s):int(s) + length] for s in np.asarray(starts)
    ]) if len(np.asarray(starts)) else np.empty(
        (0, length), flat.dtype
    )
