"""Fusion rules: which stages of a plan chain run as ONE program.

Functions from node chains to :class:`SegmentPlan` descriptions — no
execution, no compilation; the only tracing is the (cached) host-
callback probe that keeps effectful stages out of pushdown pruning.
Three rules:

* **map∘map composition** — consecutive map stages compose into one
  traced function: map_rows stages contribute their already-vmapped
  form, so row-wise chains run under a single ``vmap`` and a row-wise
  stage feeding a block-wise stage composes block-level.
* **select pushdown** — a ``select`` restricts the needed-column set; a
  backward pass over the chain prunes whole stages whose outputs nobody
  consumes and drops dead pass-through columns, so pruned columns are
  never computed, gathered, or transferred.
* **filter fusion** — a device-evaluable predicate's mask program joins
  the upstream fused run (one dispatch computes upstream outputs AND
  the mask); the row subsetting itself is a fusion barrier (its output
  row count is data-dependent), so the chain splits after it and
  downstream stages start a new segment.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Set, Tuple

from .ir import PlanNode, program_has_callback

__all__ = ["SegmentPlan", "split_segments", "plan_segment"]


@dataclasses.dataclass
class SegmentPlan:
    """The lowering-ready description of one chain segment."""

    nodes: List[PlanNode]            # the segment's nodes, in order
    included: List[PlanNode]         # map stages that actually run
    excluded: List[PlanNode]         # map stages pruned by pushdown
    final_names: List[str]           # the segment result's column names
    computed_names: List[str]        # final names produced by stages
    pass_through: List[str]          # final names read straight off source
    source_inputs: List[str]         # source columns the fused program feeds
    mask_name: Optional[str]         # filter mask output (segment-final)
    #: stage outputs computed but never materialized by the fused run —
    #: either consumed by a later stage or pruned by a select; the
    #: intermediate-bytes-avoided accounting reads this
    avoided_outputs: List[Tuple[str, object]]

    @property
    def has_filter(self) -> bool:
        return self.mask_name is not None

    @property
    def fusable(self) -> bool:
        """Worth the fused dispatch: >= 2 composed stages, a filter
        whose mask joins the upstream program, or a select that pruned
        stages/outputs. A bare single map keeps the single-verb path
        (identical behavior, including map_rows lead-dim bucketing)."""
        if len(self.included) >= 2 or self.has_filter:
            return True
        if self.excluded or self.avoided_outputs:
            return True
        return False


def split_segments(nodes: Sequence[PlanNode]) -> List[List[PlanNode]]:
    """Split a chain at filter nodes: a filter's data-dependent output
    row count bars fusing across it, so it ends its segment (its mask
    program still fuses upstream)."""
    segs: List[List[PlanNode]] = []
    cur: List[PlanNode] = []
    for n in nodes:
        cur.append(n)
        if n.kind == "filter":
            segs.append(cur)
            cur = []
    if cur:
        segs.append(cur)
    return segs


def plan_segment(
    nodes: Sequence[PlanNode],
    final_names: Sequence[str],
    source_names: Sequence[str],
) -> SegmentPlan:
    """Backward needed-columns pass over one segment.

    ``final_names`` is what the segment's consumer needs (the segment
    schema for the last segment; the next segment's source requirements
    otherwise). Stages none of whose outputs are needed are pruned —
    with their exclusive source inputs, which therefore never gather.
    """
    needed: Set[str] = set(final_names)
    mask_name: Optional[str] = None
    included_rev: List[PlanNode] = []
    excluded: List[PlanNode] = []
    for n in reversed(nodes):
        if n.kind == "filter":
            # the mask column is consumed by the subsetting step; every
            # final column passes through the filter unchanged
            mask_name = n.mask_name
            needed.add(n.mask_name)
        elif n.kind == "select":
            # downstream references are validated against the selected
            # schema at verb time, so needed is already a subset of
            # n.names; the node itself adds no requirement
            continue
        elif n.kind == "map":
            outs = set(n.out_names)
            if needed & outs or program_has_callback(n.program):
                # a host-callback stage is kept even when its outputs
                # are all dead: pruning it would elide the callback's
                # side effect, diverging from TFTPU_FUSION=0 (which
                # executes every recorded stage). Keeping it also makes
                # the lowering's callback check see it and replay the
                # segment per-stage — single-verb semantics exactly.
                included_rev.append(n)
                needed = (needed - outs) | set(n.program.input_names)
            else:
                excluded.append(n)
    included = list(reversed(included_rev))

    # forward pass: which included-stage inputs come from the source
    # (vs an earlier included stage's output)
    computed_before: Set[str] = set()
    source_inputs: List[str] = []
    for n in included:
        for i in n.program.input_names:
            if i not in computed_before and i not in source_inputs:
                source_inputs.append(i)
        computed_before |= set(n.out_names)

    src = set(source_names)
    missing = [c for c in source_inputs if c not in src]
    if missing:  # defensive: verb-time validation should make this dead
        raise ValueError(
            f"plan_segment: stage input(s) {missing} are neither source "
            f"columns ({sorted(src)}) nor upstream stage outputs"
        )

    computed = [n for n in final_names if n in computed_before]
    if mask_name is not None and mask_name not in computed:
        computed = computed + [mask_name]
    pass_through = [n for n in final_names if n not in computed_before]
    stray = [c for c in pass_through if c not in src]
    if stray:  # defensive, as above
        raise ValueError(
            f"plan_segment: final column(s) {stray} are neither computed "
            "by a stage nor present on the source"
        )

    fused_outputs = set(computed)
    avoided: List[Tuple[str, object]] = []
    for n in included:
        for o in (n.program.outputs or []):
            if o.name not in fused_outputs:
                avoided.append((o.name, o))
    for n in excluded:
        for o in (n.program.outputs or []):
            avoided.append((o.name, o))

    return SegmentPlan(
        nodes=list(nodes),
        included=included,
        excluded=excluded,
        final_names=list(final_names),
        computed_names=computed,
        pass_through=pass_through,
        source_inputs=source_inputs,
        mask_name=mask_name,
        avoided_outputs=avoided,
    )
