"""Fusion rules: which stages of a plan chain run as ONE program.

Functions from node chains to :class:`SegmentPlan` descriptions — no
execution, no compilation; the only tracing is the (cached) host-
callback probe that keeps effectful stages out of pushdown pruning.
Four rules:

* **map∘map composition** — consecutive map stages compose into one
  traced function: map_rows stages contribute their already-vmapped
  form, so row-wise chains run under a single ``vmap`` and a row-wise
  stage feeding a block-wise stage composes block-level.
* **select pushdown** — a ``select`` restricts the needed-column set; a
  backward pass over the chain prunes whole stages whose outputs nobody
  consumes and drops dead pass-through columns, so pruned columns are
  never computed, gathered, or transferred.
* **filter fusion** — a device-evaluable predicate's mask program joins
  the upstream fused run (one dispatch computes upstream outputs AND
  the mask); the row subsetting itself is a fusion barrier (its output
  row count is data-dependent), so the chain splits after it and
  downstream stages start a new segment.
* **join pushdown** — a trailing ``join`` node ends its segment (its
  output row count is data-dependent, like a filter's), but the
  needed-columns pass maps the segment's requirements back THROUGH the
  join's rename tables: only the probe-side originals of needed output
  columns flow into the upstream map fusion, and only the needed
  build-side columns are read off the right frame.

The module also hosts the **cost model** (:class:`Decision` and the
``decide_*`` functions): pure functions from segment descriptions +
memoized ``Program.cost_analysis`` + live metric readings to lowering
choices (fuse vs split, aggregate-epilogue strategy, segment-count
bucketing). The lowering (:mod:`.lower`) counts and traces every
decision; this module never touches metrics itself.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .ir import PlanNode, program_has_callback
from .stats import STRATEGY_WALL_MIN_SAMPLES

__all__ = [
    "SegmentPlan",
    "split_segments",
    "plan_segment",
    "Decision",
    "decide_fuse",
    "decide_epilogue",
    "decide_segment_bucket",
    "decide_segment_reduce",
    "decide_decode_attention",
    "decide_ragged_gather",
    "reassoc_safe",
    "PushdownPlan",
    "PushdownLevel",
    "plan_pushdown",
    "decide_pushdown",
    "plan_join_chain",
    "decide_join_order",
    "warm_segment_bucket",
    "PUSHDOWN_MIN_SURVIVAL",
    "LATENCY_FLIP_MARGIN",
    "pick_by_observed_wall",
]


@dataclasses.dataclass
class SegmentPlan:
    """The lowering-ready description of one chain segment."""

    nodes: List[PlanNode]            # the segment's nodes, in order
    included: List[PlanNode]         # map stages that actually run
    excluded: List[PlanNode]         # map stages pruned by pushdown
    final_names: List[str]           # the segment result's column names
    computed_names: List[str]        # final names produced by stages
    pass_through: List[str]          # final names read straight off source
    source_inputs: List[str]         # source columns the fused program feeds
    mask_name: Optional[str]         # filter mask output (segment-final)
    #: stage outputs computed but never materialized by the fused run —
    #: either consumed by a later stage or pruned by a select; the
    #: intermediate-bytes-avoided accounting reads this
    avoided_outputs: List[Tuple[str, object]]
    #: trailing join node (the segment ends at it) and the pruned
    #: column sets the join actually reads: ``final_names`` then names
    #: the PROBE-side columns the upstream fusion must produce, while
    #: ``join_out_names`` names the join outputs the consumer needs.
    join_node: Optional[PlanNode] = None
    right_needed: Optional[List[str]] = None
    join_out_names: Optional[List[str]] = None

    @property
    def has_filter(self) -> bool:
        return self.mask_name is not None

    @property
    def has_join(self) -> bool:
        return self.join_node is not None

    @property
    def fusable(self) -> bool:
        """Worth the fused dispatch: >= 2 composed stages, a filter
        whose mask joins the upstream program, or a select that pruned
        stages/outputs. A bare single map keeps the single-verb path
        (identical behavior, including map_rows lead-dim bucketing)."""
        if len(self.included) >= 2 or self.has_filter:
            return True
        if self.excluded or self.avoided_outputs:
            return True
        return False


def split_segments(nodes: Sequence[PlanNode]) -> List[List[PlanNode]]:
    """Split a chain at filter and join nodes: both have data-dependent
    output row counts, which bars fusing across them, so each ends its
    segment (a filter's mask program — and a join's probe-side maps —
    still fuse upstream)."""
    segs: List[List[PlanNode]] = []
    cur: List[PlanNode] = []
    for n in nodes:
        cur.append(n)
        if n.kind in ("filter", "join"):
            segs.append(cur)
            cur = []
    if cur:
        segs.append(cur)
    return segs


def plan_segment(
    nodes: Sequence[PlanNode],
    final_names: Sequence[str],
    source_names: Sequence[str],
) -> SegmentPlan:
    """Backward needed-columns pass over one segment.

    ``final_names`` is what the segment's consumer needs (the segment
    schema for the last segment; the next segment's source requirements
    otherwise). Stages none of whose outputs are needed are pruned —
    with their exclusive source inputs, which therefore never gather.

    A segment ending in a ``join`` node maps the needed output columns
    back through the join's rename tables first: the backward pass then
    runs over the probe-side stages with the probe-side requirements,
    and the build-side requirements are recorded as ``right_needed``.
    """
    nodes = list(nodes)
    join_node: Optional[PlanNode] = None
    right_needed: Optional[List[str]] = None
    join_out_names: Optional[List[str]] = None
    if nodes and nodes[-1].kind == "join":
        join_node = nodes[-1]
        spec = join_node.spec
        # keys are always required on both sides (they drive the match);
        # non-key outputs map back to their side's original name
        inv_l = {out: orig for orig, out in spec.lname}
        inv_r = {out: orig for orig, out in spec.rname}
        join_out_names = [
            n for n in join_node.schema.names
            if n in set(final_names) or n in spec.keys
        ]
        left_needed = list(spec.keys)
        right_needed = list(spec.keys)
        for name in join_out_names:
            if name in spec.keys:
                continue
            if name in inv_l:
                left_needed.append(inv_l[name])
            elif name in inv_r:
                right_needed.append(inv_r[name])
        nodes = nodes[:-1]
        final_names = left_needed

    needed: Set[str] = set(final_names)
    mask_name: Optional[str] = None
    included_rev: List[PlanNode] = []
    excluded: List[PlanNode] = []
    for n in reversed(nodes):
        if n.kind == "filter":
            # the mask column is consumed by the subsetting step; every
            # final column passes through the filter unchanged
            mask_name = n.mask_name
            needed.add(n.mask_name)
        elif n.kind == "select":
            # downstream references are validated against the selected
            # schema at verb time, so needed is already a subset of
            # n.names; the node itself adds no requirement
            continue
        elif n.kind == "map":
            outs = set(n.out_names)
            if needed & outs or program_has_callback(n.program):
                # a host-callback stage is kept even when its outputs
                # are all dead: pruning it would elide the callback's
                # side effect, diverging from TFTPU_FUSION=0 (which
                # executes every recorded stage). Keeping it also makes
                # the lowering's callback check see it and replay the
                # segment per-stage — single-verb semantics exactly.
                included_rev.append(n)
                needed = (needed - outs) | set(n.program.input_names)
            else:
                excluded.append(n)
    included = list(reversed(included_rev))

    # forward pass: which included-stage inputs come from the source
    # (vs an earlier included stage's output)
    computed_before: Set[str] = set()
    source_inputs: List[str] = []
    for n in included:
        for i in n.program.input_names:
            if i not in computed_before and i not in source_inputs:
                source_inputs.append(i)
        computed_before |= set(n.out_names)

    src = set(source_names)
    missing = [c for c in source_inputs if c not in src]
    if missing:  # defensive: verb-time validation should make this dead
        raise ValueError(
            f"plan_segment: stage input(s) {missing} are neither source "
            f"columns ({sorted(src)}) nor upstream stage outputs"
        )

    computed = [n for n in final_names if n in computed_before]
    if mask_name is not None and mask_name not in computed:
        computed = computed + [mask_name]
    pass_through = [n for n in final_names if n not in computed_before]
    stray = [c for c in pass_through if c not in src]
    if stray:  # defensive, as above
        raise ValueError(
            f"plan_segment: final column(s) {stray} are neither computed "
            "by a stage nor present on the source"
        )

    fused_outputs = set(computed)
    avoided: List[Tuple[str, object]] = []
    for n in included:
        for o in (n.program.outputs or []):
            if o.name not in fused_outputs:
                avoided.append((o.name, o))
    for n in excluded:
        for o in (n.program.outputs or []):
            avoided.append((o.name, o))

    # NOTE: ``nodes`` holds the segment's INNER (pre-join) nodes only;
    # the trailing join rides in ``join_node`` so the per-stage replay
    # and the fused result schema both see the probe-side chain.
    return SegmentPlan(
        nodes=list(nodes),
        included=included,
        excluded=excluded,
        final_names=list(final_names),
        computed_names=computed,
        pass_through=pass_through,
        source_inputs=source_inputs,
        mask_name=mask_name,
        avoided_outputs=avoided,
        join_node=join_node,
        right_needed=right_needed,
        join_out_names=join_out_names,
    )


# ---------------------------------------------------------------------------
# cost model: pure decision functions. The lowering counts + traces
# every Decision (tftpu_plan_cost_decisions_total{decision=}); this
# module only DECIDES, consulting the memoized Program.cost_analysis
# and whatever live metric readings the caller hands in.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Decision:
    """One lowering choice: ``kind`` is the pre-registered counter label
    (``fuse`` | ``split_single_stage`` | ``epilogue_per_block`` |
    ``epilogue_concat`` | ``bucket_segments``), ``reason`` the
    human-readable why, ``details`` the numbers that drove it (logged on
    the trace event so a decision is reconstructible post-hoc)."""

    kind: str
    reason: str
    details: Dict[str, object] = dataclasses.field(default_factory=dict)


#: An observed-wall flip engages only when the alternative's EWMA beats
#: the static choice's by at least this factor — hysteresis against
#: noisy walls oscillating the strategy (and retracing) every force.
LATENCY_FLIP_MARGIN = 0.8


def pick_by_observed_wall(
    static_kind: str,
    alternatives: Sequence[str],
    observed_walls: Optional[Dict[str, dict]],
) -> Optional[Tuple[str, Dict[str, object]]]:
    """The latency-feedback core shared by every ``decide_*``: given the
    statically-preferred strategy, the alternatives the CALLER verified
    are eligible AND bit-identical for this workload, and the observed
    per-strategy wall table (:func:`..stats.strategy_walls`), pick the
    observed-fastest alternative when it beats the static choice's EWMA
    by :data:`LATENCY_FLIP_MARGIN` with enough samples on both sides.
    Returns ``(flipped_kind, evidence_details)`` or None (keep static).
    """
    if not observed_walls:
        return None
    cur = observed_walls.get(static_kind)
    if not cur or int(cur.get("n", 0)) < STRATEGY_WALL_MIN_SAMPLES:
        return None
    cur_w = float(cur.get("ewma_s", 0.0))
    best: Optional[Tuple[str, float]] = None
    for alt in alternatives:
        if alt == static_kind:
            continue
        ent = observed_walls.get(alt)
        if not ent or int(ent.get("n", 0)) < STRATEGY_WALL_MIN_SAMPLES:
            continue
        w = float(ent.get("ewma_s", 0.0))
        if w < cur_w * LATENCY_FLIP_MARGIN and (
            best is None or w < best[1]
        ):
            best = (alt, w)
    if best is None:
        return None
    alt, w = best
    return alt, {
        "latency_flip": True,
        "observed_wall_s": {
            static_kind: round(cur_w, 6), alt: round(w, 6),
        },
        "wall_samples": {
            static_kind: int(cur.get("n", 0)),
            alt: int(observed_walls[alt].get("n", 0)),
        },
    }


def _stage_costs(plan: SegmentPlan) -> Dict[str, float]:
    """Summed memoized cost_analysis over the segment's included stages
    (zero when a backend reports no costs, as some CPU builds do) —
    never compiles: Program.cost_analysis memoizes per probe."""
    flops = 0.0
    bytes_accessed = 0.0
    for n in plan.included:
        try:
            c = n.program.cost_analysis()
            flops += float(c.get("flops", 0.0) or 0.0)
            bytes_accessed += float(c.get("bytes accessed", 0.0) or 0.0)
        except Exception:  # pragma: no cover - cost query must not gate
            pass
    return {"flops": flops, "bytes_accessed": bytes_accessed}


def decide_fuse(
    plan: SegmentPlan, lowering_seconds_mean: Optional[float] = None,
    observed_walls: Optional[Dict[str, dict]] = None,
) -> Decision:
    """Fuse-vs-split for one map segment. Composition is essentially
    always a win once two stages (or a mask/pruning select) are in
    play: the fused dispatch saves one executor round-trip, one
    device<->host materialization, and one output-validation pass PER
    ELIDED STAGE, while the composed program's cost is the sum of its
    parts (XLA re-fuses the elementwise chain). A bare single map keeps
    the single-verb path — fusing it buys nothing and would bypass the
    specialized lead-dim bucketing.

    ``observed_walls`` (the stats sidecar's per-strategy EWMA table)
    can flip a fusable segment BACK to the per-stage replay when the
    measured per-stage wall beats the fused wall — the replay is the
    TFTPU_FUSION=0 path, bit-identical by the core contract, so the
    flip is always safe."""
    details = _stage_costs(plan)
    details["stages"] = len(plan.included)
    if lowering_seconds_mean is not None:
        details["lowering_seconds_mean"] = round(lowering_seconds_mean, 6)
    if plan.fusable:
        flip = pick_by_observed_wall(
            "fuse", ("split_single_stage",), observed_walls
        )
        if flip is not None:
            kind, evidence = flip
            details.update(evidence)
            return Decision(
                kind,
                "observed walls: the per-stage replay runs faster than "
                "the fused dispatch for this workload (bit-identical — "
                "it IS the TFTPU_FUSION=0 path)",
                details,
            )
        why = (
            f"{len(plan.included)} composable stage(s)"
            + (", mask fuses upstream" if plan.has_filter else "")
            + (
                f", {len(plan.excluded)} stage(s) pruned"
                if plan.excluded else ""
            )
        )
        return Decision("fuse", why, details)
    return Decision(
        "split_single_stage",
        "bare single map keeps the specialized single-verb path "
        "(lead-dim bucketing included); fusing buys no elided dispatch",
        details,
    )


#: ops whose cross-block tree-combine is exact for ANY value dtype
_EXACT_COMBINE_OPS = ("reduce_min", "reduce_max")


def reassoc_safe(op: str, np_dtype) -> bool:
    """True when per-block partials of ``op`` tree-combine to the SAME
    bits as one global reduction over row order: min/max always (order
    free); sum/mean only for integer/bool values (exact associative
    arithmetic). Float sums reassociate — the bit-identical contract
    then requires the concat epilogue instead."""
    import numpy as _np

    if op in _EXACT_COMBINE_OPS:
        return True
    kind = _np.dtype(np_dtype).kind
    return kind in ("i", "u", "b")


def incremental_fold_safe(op: str, np_dtype) -> bool:
    """True when per-CHUNK partials of ``op`` fold across arriving scan
    chunks to the same bits as one aggregation over the whole table —
    the eligibility gate of registered-query incremental maintenance
    (ISSUE 20). Strictly the :func:`reassoc_safe` contract minus
    ``reduce_mean``: a mean's partials fold only as a (sum, count)
    companion pair, which the partial tables don't carry yet (a named
    TFG114 decline, not a wrong answer). min/max fold exactly for any
    dtype; sums only for integer/bool accumulation — a float sum's
    fold order differs from the global reduction's row order."""
    if op == "reduce_mean":
        return False
    return reassoc_safe(op, np_dtype)


def decide_epilogue(
    ops_and_dtypes: Sequence[Tuple[str, object]],
    num_groups: int,
    value_bytes: float,
    observed_walls: Optional[Dict[str, dict]] = None,
) -> Decision:
    """Aggregate-epilogue strategy for a fused map→aggregate segment.

    * ``epilogue_per_block`` — the segment reduction fuses INTO each
      block's program (one dispatch per block yields a ``[K, ...]``
      partial table; tables tree-combine). Chosen when every (op,
      value-dtype) pair is reassociation-safe: the combine is then
      bit-identical to the unfused global reduction.
    * ``epilogue_concat`` — the fused map runs per block with outputs
      kept on device, the concatenated values feed ONE segment-reduce
      dispatch (the very program the unfused host path runs, over the
      same values in the same row order — bit-identical by
      construction, at the cost of holding the mapped columns in
      device memory once).

    When every op is reassociation-safe BOTH strategies are exact, so
    the choice is pure latency: ``observed_walls`` (the stats
    sidecar's per-strategy EWMA table) flips per_block → concat when
    the concat epilogue measured faster. Unsafe ops always take concat
    (correctness, never overridden).
    """
    unsafe = [
        (op, str(getattr(dt, "name", dt)))
        for op, dt in ops_and_dtypes
        if not reassoc_safe(op, dt)
    ]
    details = {
        "num_groups": int(num_groups),
        "value_bytes": int(value_bytes),
        "ops": [op for op, _ in ops_and_dtypes],
    }
    if not unsafe:
        flip = pick_by_observed_wall(
            "epilogue_per_block", ("epilogue_concat",), observed_walls
        )
        if flip is not None:
            kind, evidence = flip
            details.update(evidence)
            return Decision(
                kind,
                "observed walls: the concat epilogue runs faster than "
                "per-block partial tables for this workload (both are "
                "exact for reassociation-safe ops — bit-identical "
                "either way)",
                details,
            )
        return Decision(
            "epilogue_per_block",
            "all ops tree-combine exactly (min/max or integer sums): "
            "per-block partial tables, mapped columns never leave the "
            "dispatch",
            details,
        )
    return Decision(
        "epilogue_concat",
        "float sum/mean reassociates across blocks — one segment "
        f"dispatch over device-concatenated values keeps {unsafe} "
        "bit-identical to the unfused path",
        details,
    )


# ---------------------------------------------------------------------------
# kernel selection (ISSUE 12): which LOWERING serves each straggler —
# the pallas kernel, the jitted XLA program, or the host path. Pure
# decisions; the dispatch sites count them through _note_decision and
# the compile-cache fingerprint carries kernels.fingerprint_token() so
# a selection flip can never serve a stale executable.
# ---------------------------------------------------------------------------

def _kernel_backend_ok() -> bool:
    """Kernels engage on TPU-family backends, or anywhere under the
    ``TFTPU_PALLAS_FORCE`` test/bench hook (the pallas CPU interpreter
    runs them — slow, but the full selection wiring executes)."""
    import jax

    from .. import kernels

    if not kernels.enabled():
        return False
    if kernels.force_active():
        return True
    return jax.default_backend() in ("tpu", "axon")


def _force_pins_kernels() -> bool:
    """Under ``TFTPU_PALLAS_FORCE`` the kernel lowering is pinned by the
    test/bench hook: latency flips must not engage (the hook exists to
    exercise a SPECIFIC lowering) and interpreted-kernel walls are not
    representative of any real backend anyway."""
    from .. import kernels

    return kernels.force_active()


def decide_segment_reduce(
    ops_key, val_cols, num_segments: int,
    observed_walls: Optional[Dict[str, dict]] = None,
) -> Decision:
    """Keyed-reduction strategy for one segment: ``host_segment_reduce``
    (CPU bincount — the measured XLA:CPU-scatter escape, unchanged),
    ``pallas_segment_reduce`` (the fused multi-op kernel,
    ``kernels/segment_reduce.py``), or ``jit_segment_reduce`` (the
    jitted scatter program). Order matters: the host path keeps CPU
    float sums (its f64 accumulation is the tighter bound and bincount
    beats interpreted pallas by orders of magnitude); the kernel takes
    whatever remains eligible on a kernel-capable backend.

    ``observed_walls`` may flip the static choice to an eligible
    alternative that measured faster — but ONLY when every (op, value
    dtype) is :func:`reassoc_safe` (min/max, integer sums): those
    reduce to the same bits under every strategy, so the flip cannot
    move results. Float sums pin their statically-chosen strategy (the
    host path's f64 accumulation is not bit-identical to the scatter
    program's)."""
    from ..kernels import segment_reduce as _ksr
    from ..ops.segment import host_segment_eligible

    details = {
        "num_groups": int(num_segments),
        "ops": [op for _, op in ops_key],
    }
    candidates = ["jit_segment_reduce"]
    if host_segment_eligible(ops_key, val_cols):
        static = Decision(
            "host_segment_reduce",
            "CPU backend: bincount's weighted histogram beats XLA's "
            "serialized segment scatter for float sums",
            details,
        )
        candidates.append("host_segment_reduce")
    elif _kernel_backend_ok() and _ksr.eligible(
        ops_key, val_cols, num_segments
    ):
        static = Decision(
            "pallas_segment_reduce",
            "fused multi-op pallas kernel: every (column, op) partial "
            "in ONE dispatch (one-hot MXU sums, masked VPU min/max) "
            "instead of one scatter per fetch",
            details,
        )
        candidates.append("pallas_segment_reduce")
    else:
        static = Decision(
            "jit_segment_reduce",
            "jitted XLA segment program (kernel ineligible or disabled)",
            details,
        )
    all_exact = all(
        x in val_cols and reassoc_safe(op, val_cols[x].dtype)
        for x, op in ops_key
    )
    if not all_exact or _force_pins_kernels():
        return static
    flip = pick_by_observed_wall(static.kind, candidates, observed_walls)
    if flip is None:
        return static
    kind, evidence = flip
    details = dict(details)
    details.update(evidence)
    return Decision(
        kind,
        f"observed walls: {kind} runs faster than {static.kind} for "
        "this workload (all ops reassociation-safe — every strategy "
        "reduces to the same bits)",
        details,
    )


def decide_decode_attention(
    num_heads: int, head_dim: int, page_size: int, max_pages: int,
    observed_walls: Optional[Dict[str, dict]] = None,
) -> Decision:
    """Decode-attention lowering for a serving decode engine, chosen
    ONCE at engine build (both the batched and the solo step trace the
    same choice — the batched==solo and preemption-replay bit-identity
    gates therefore hold whichever side wins). ``observed_walls`` can
    flip pallas → XLA when recorded step walls show the kernel slower
    on this host (the kernel is bit-identical to the XLA chain, so the
    flip cannot move tokens); the reverse flip never engages — XLA is
    only static when the kernel backend is unavailable."""
    details = {
        "heads": int(num_heads), "head_dim": int(head_dim),
        "page_size": int(page_size), "max_pages": int(max_pages),
    }
    if _kernel_backend_ok():
        flip = None if _force_pins_kernels() else pick_by_observed_wall(
            "pallas_decode_attn", ("xla_decode_attn",), observed_walls
        )
        if flip is not None:
            kind, evidence = flip
            details.update(evidence)
            return Decision(
                kind,
                "observed walls: the XLA gather→dequant→attend chain "
                "steps faster than the paged kernel on this host "
                "(bit-identical — the kernel gate proves it)",
                details,
            )
        return Decision(
            "pallas_decode_attn",
            "fused paged int8-KV kernel: pages stream HBM→VMEM through "
            "the scalar-prefetched page table and dequantize "
            "in-register — no materialized gather copy",
            details,
        )
    return Decision(
        "xla_decode_attn",
        "XLA gather→dequant→attend chain (kernels disabled or no "
        "Mosaic backend)",
        details,
    )


def decide_ragged_gather(
    n_rows: int, n_groups: int, cell_dtype,
    observed_walls: Optional[Dict[str, dict]] = None,
) -> Optional[Decision]:
    """Ragged map_rows staging: the pallas flat-buffer gather
    (``pallas_ragged_gather``) when the single-1-D-ragged-column fast
    path applies on a kernel-capable backend; None keeps the host
    ``np.stack`` staging (not a counted decision — it is the ordinary
    path, not a choice). The caller additionally verifies the cell
    shapes and the int32 offset bound before acting on the choice.
    ``observed_walls`` flips the kernel BACK to host staging (returns
    None) when recorded walls show ``host_stack`` faster — staging is
    bit-identical either way, so the flip only moves time."""
    import numpy as _np

    if n_rows == 0 or not _kernel_backend_ok():
        return None
    if _np.dtype(cell_dtype).kind not in ("f", "i", "u", "b"):
        return None
    if not _force_pins_kernels() and pick_by_observed_wall(
        "pallas_ragged_gather", ("host_stack",), observed_walls
    ) is not None:
        return None
    return Decision(
        "pallas_ragged_gather",
        "single 1-D ragged column: cells move as one flat buffer and "
        "the kernel stages each shape group's padded batch on device "
        f"({n_groups} shape group(s) — host np.stack and per-group "
        "transfers eliminated)",
        {"rows": int(n_rows), "shape_groups": int(n_groups)},
    )


# ---------------------------------------------------------------------------
# adaptive optimizer (ISSUE 14): aggregate pushdown below joins, join
# reordering, and stats-fed re-optimization. Pure planning/decision
# functions — the lowering executes and counts; TFTPU_REOPT=0 keeps
# all of it off. Every rewrite here is gated on exactness: only
# reassoc_safe (op, dtype) pairs push below a join, only m=1 joins
# (unique build keys, verified at runtime by the lowering) rewrite at
# all, so the rewritten plan is bit-identical to TFTPU_FUSION=0 by
# construction — group encoding is lexicographic (ops/keys.py), hence
# row-order independent, and the surviving-group filter preserves it.
# ---------------------------------------------------------------------------

#: Observed fraction of base rows surviving the pushed-below joins
#: under which pushdown is re-optimized AWAY: aggregating everything
#: below the join costs O(base rows), while highly selective joins
#: leave the aggregate-above path with far fewer rows to reduce.
PUSHDOWN_MIN_SURVIVAL = 0.05


@dataclasses.dataclass
class PushdownLevel:
    """One join the aggregate pushes below (outermost level first)."""

    plan_index: int          # index of the join's segment in ``plans``
    spec: object             # the join's _JoinSpec
    how: str
    #: group-key OUTPUT names aligned 1:1 with ``spec.keys`` — the
    #: lowering's semi-join filter reads these group key columns.
    key_finals: List[str]


@dataclasses.dataclass
class PushdownPlan:
    """Lowering-ready description of an aggregate-below-join rewrite."""

    side: str                # 'left' (probe chain) | 'right' (build frame)
    start: int               # plans index of the innermost pushed segment
    levels: List[PushdownLevel]
    key_base: List[str]      # group-key originals at the pushed side
    val_base: Dict[str, str]  # fetch output name -> pushed-side original


def _miss(cause: str, subject: str, detail: str, fix: str) -> Dict[str, str]:
    return {"cause": cause, "subject": subject, "detail": detail,
            "fix": fix}


def plan_pushdown(plans, keys, seg_info, agg_schema):
    """Static eligibility walk for aggregate pushdown below a trailing
    join chain. Returns ``(PushdownPlan | None, misses)`` — ``misses``
    holds the *fixable* blocking causes (the TFG110 evidence: each
    names the blocking column/fetch and a fix). Pure: no execution, no
    forcing; the runtime conditions (unique build-side keys, dense
    value cells) are verified by the lowering, which falls back to the
    static path when they fail.

    Eligibility (every rewrite bit-identical to ``TFTPU_FUSION=0``):

    * every fetch's (op, value dtype) is :func:`reassoc_safe` — the
      order-sensitive float sums/means PR 7 already excludes from
      tree-combining stay excluded here;
    * walking joins outermost→inner, the group keys and every value
      column map to ONE side (join keys live on both); the probe
      (left) side may be descended through multiple bare join
      segments, the build (right) side only at the outermost level
      under ``how='inner'``;
    * each pushed join's keys are covered by the group keys (the group
      then functionally determines the join key, so a group is matched
      or unmatched as a whole — the join degenerates to a semi-join
      filter over whole groups);
    * ``how`` is ``inner`` (groups filter to matched keys) or ``left``
      (no filter) — ``outer`` appends fill-valued rows and never
      pushes.
    """
    misses: List[Dict[str, str]] = []
    L = len(plans)
    if L == 0 or not plans[L - 1].has_join:
        return None, misses
    unsafe = []
    for x, op, _ in seg_info:
        np_dt = getattr(agg_schema[x].dtype, "np_dtype", None)
        if np_dt is None or not reassoc_safe(op, np_dt):
            unsafe.append((x, op))
    if unsafe:
        for x, op in unsafe:
            misses.append(_miss(
                "float_reassoc", x,
                f"fetch {x!r} ({op}) reassociates: a float sum/mean "
                "computed below the join is not bit-identical to the "
                "unfused reduction over joined rows",
                f"aggregate an integer-typed column, or accept the "
                f"epilogue-above path for {x!r} (bit-identity is "
                "mandatory, so order-sensitive float reductions never "
                "push below joins)",
            ))
        return None, misses

    # needs: final (aggregate-schema) name -> name at the current level
    needs: Dict[str, str] = {
        n: n for n in list(keys) + [x for x, _, _ in seg_info]
    }
    levels: List[PushdownLevel] = []
    side: Optional[str] = None
    i = L - 1
    start = i
    while i >= 0 and plans[i].has_join:
        spec = plans[i].join_node.spec
        inv_l = {out: orig for orig, out in spec.lname}
        inv_r = {out: orig for orig, out in spec.rname}
        cur_to_final = {cur: fin for fin, cur in needs.items()}
        gcur = {needs[f] for f in keys}
        missing = [k for k in spec.keys if k not in gcur]
        if missing:
            misses.append(_miss(
                "key_not_grouped", missing[0],
                f"join key(s) {missing} are not group keys, so a group "
                "can span matched and unmatched join keys — the join "
                "cannot degenerate to a whole-group semi-join filter",
                f"group by {missing} as well (the join key then rides "
                "the group), or aggregate before joining",
            ))
            break
        mapped: Dict[str, str] = {}
        left_cols, right_cols = [], []
        for fin, cur in needs.items():
            if cur in spec.keys:
                mapped[fin] = cur
            elif cur in inv_l:
                mapped[fin] = inv_l[cur]
                left_cols.append(fin)
            elif cur in inv_r:
                mapped[fin] = inv_r[cur]
                right_cols.append(fin)
        if right_cols and left_cols:
            misses.append(_miss(
                "mixed_sides", right_cols[0],
                f"column(s) {sorted(left_cols)} come from the probe "
                f"side but {sorted(right_cols)} from the build side — "
                "a partial aggregate below either side cannot produce "
                "both",
                "restrict the group keys and fetches to one side of "
                "the join (join keys count as either side)",
            ))
            break
        if right_cols:
            # build-side pushdown: outermost level only, inner only —
            # unmatched probe rows under how='left' would inject fill
            # values into the groups.
            if levels:
                misses.append(_miss(
                    "mixed_sides", right_cols[0],
                    f"column(s) {sorted(right_cols)} come from an "
                    "inner join's build side below an already-pushed "
                    "level",
                    "restrict the fetches to the probe side, or "
                    "aggregate before the outer joins",
                ))
                break
            if spec.how != "inner":
                misses.append(_miss(
                    "outer_or_left_build", right_cols[0],
                    f"how={spec.how!r} keeps unmatched probe rows "
                    "whose build-side columns take fill values — fills "
                    "would enter the pushed-down groups",
                    "use an inner join, or aggregate probe-side "
                    "columns instead",
                ))
                break
            side = "right"
            levels.append(PushdownLevel(
                plan_index=i, spec=spec, how=spec.how,
                key_finals=[cur_to_final[k] for k in spec.keys],
            ))
            needs = mapped
            start = i
            break
        # probe-side descent
        if spec.how not in ("inner", "left"):
            misses.append(_miss(
                "outer_join", "+".join(spec.keys),
                f"how={spec.how!r} appends unmatched build rows with "
                "fill-valued probe columns — fills would enter the "
                "pushed-down groups",
                "use an inner or left join, or aggregate before "
                "joining",
            ))
            break
        side = "left"
        levels.append(PushdownLevel(
            plan_index=i, spec=spec, how=spec.how,
            key_finals=[cur_to_final[k] for k in spec.keys],
        ))
        needs = mapped
        start = i
        if plans[i].included or i == 0:
            # this segment's own map stages compute below its join —
            # it becomes the base level (maps run, aggregate above
            # them, semi-join filters above that)
            break
        i -= 1
    if not levels:
        return None, misses
    return PushdownPlan(
        side=side,
        start=start,
        levels=levels,
        key_base=[needs[f] for f in keys],
        val_base={x: needs[x] for x, _, _ in seg_info},
    ), misses


def decide_pushdown(
    push: PushdownPlan, stats_record: Optional[dict]
) -> Tuple[bool, Decision, bool]:
    """Push-vs-keep for an eligible aggregate-below-join rewrite.
    Statically pushdown always wins (the join's match expansion and
    gather disappear); the observed-survival feedback re-optimizes it
    AWAY when a previous execution measured that the joins discard
    almost every row (aggregating the full base side then costs more
    than joining first). Returns ``(push?, decision, used_stats)``."""
    details: Dict[str, object] = {
        "levels": len(push.levels), "side": push.side,
    }
    survival = None
    if stats_record:
        survival = (stats_record.get("push") or {}).get("survival")
    if survival is not None:
        details["observed_survival"] = round(float(survival), 4)
        if float(survival) < PUSHDOWN_MIN_SURVIVAL:
            return False, Decision(
                "pushdown_skipped_selective",
                f"observed survival {float(survival):.3f} < "
                f"{PUSHDOWN_MIN_SURVIVAL}: the joins discard nearly "
                "every row, so aggregating above them reduces far "
                "fewer rows than the full pushed-down side",
                details,
            ), True
        return True, Decision(
            "pushdown_aggregate",
            f"{len(push.levels)} join(s) degenerate to whole-group "
            "semi-join filters (observed survival "
            f"{float(survival):.3f}): partial aggregate runs below, "
            "rows never match-expand",
            details,
        ), True
    return True, Decision(
        "pushdown_aggregate",
        f"{len(push.levels)} join(s) degenerate to whole-group "
        "semi-join filters: partial aggregate runs below, rows never "
        "match-expand through the join",
        details,
    ), False


# ---------------------------------------------------------------------------
# multi-join reordering
# ---------------------------------------------------------------------------

def plan_join_chain(jplans) -> Tuple[Optional[dict], str]:
    """Static eligibility + rename maps for reordering a run of
    consecutive join segments. Returns ``(chain_info, reason)`` —
    ``chain_info`` is None when ineligible (``reason`` says why).

    Eligibility (reordering must be bit-identical, like every rewrite):

    * every join is ``inner`` (left/outer fills depend on position);
    * every join's keys trace back to the BASE probe frame (a key
      produced by an earlier join's build side pins that order);
    * no build-side chain contains a host callback (reordering would
      reorder its side effects);
    * with the runtime m=1 condition (unique build keys, checked by
      the lowering), inner joins then commute: the output rows are the
      base rows, in base order, that match EVERY build side — the same
      set whatever the order.

    ``chain_info`` maps every column to its FINAL (output-schema) name
    so the lowering can pre-rename both sides and execute the joins in
    any order without rename chains interfering:

    * ``base_rename``: base column -> final name;
    * per level: ``exec_keys`` (final key names), ``right_rename``
      (build column -> final, key columns included), ``key_base``
      (base-frame names of the keys, for stats/selectivity).
    """
    from .ir import program_has_callback, resolve_chain

    for p in jplans:
        if p.join_node.spec.how != "inner":
            return None, f"how={p.join_node.spec.how!r} join pins its " \
                         "position (only inner joins commute)"
    for p in jplans:
        right = p.join_node.right
        node = getattr(right, "_plan", None)
        if node is not None and not right.is_materialized:
            _, rnodes = resolve_chain(node)
            if any(
                n.kind == "map" and program_has_callback(n.program)
                for n in rnodes
            ):
                return None, "a build-side chain contains a host " \
                             "callback (reordering would reorder its " \
                             "side effects)"

    base_names = list(jplans[0].final_names)
    live: Dict[str, Tuple[str, object]] = {
        n: ("base", n) for n in base_names
    }
    levels: List[dict] = []
    for i, p in enumerate(jplans):
        spec = p.join_node.spec
        lname = dict(spec.lname)
        key_base = []
        for k in spec.keys:
            if k not in live:
                return None, f"join key {k!r} is not visible on the " \
                             "pruned probe side"
            tag, orig = live[k]
            if tag != "base":
                return None, f"join key {k!r} comes from an earlier " \
                             "join's build side — that join must run " \
                             "first"
            key_base.append(orig)
        new_live: Dict[str, Tuple[str, object]] = {}
        for n, origin in live.items():
            if n in spec.keys:
                new_live[n] = origin
            elif n in lname:
                new_live[lname[n]] = origin
            else:  # pragma: no cover - lname covers the full schema
                return None, f"column {n!r} has no rename entry at " \
                             f"join {i}"
        needed_r = set(p.right_needed or [])
        for orig, out in spec.rname:
            if orig in needed_r:
                new_live[out] = (f"right{i}", orig)
        levels.append({"spec": spec, "keys": tuple(spec.keys),
                       "key_base": key_base})
        live = new_live

    finals = list(live)
    if len(set(finals)) != len(finals):  # pragma: no cover - defensive
        return None, "final column names collide"
    base_rename = {orig: fin for fin, (tag, orig) in live.items()
                   if tag == "base"}
    for i, (lev, p) in enumerate(zip(levels, jplans)):
        spec = lev["spec"]
        rr = {orig: fin for fin, (tag, orig) in live.items()
              if tag == f"right{i}"}
        for k, kb in zip(lev["keys"], lev["key_base"]):
            rr[k] = base_rename[kb]
        lev["right_rename"] = rr
        lev["exec_keys"] = tuple(
            base_rename[kb] for kb in lev["key_base"]
        )
        lev["nonkey_finals"] = tuple(
            fin for fin, (tag, _) in live.items() if tag == f"right{i}"
        )
    return {
        "base_rename": base_rename,
        "levels": levels,
        "all_finals": finals,
    }, ""


def decide_join_order(
    build_rows: Sequence[int],
    observed_sels: Sequence[Optional[float]],
    estimates: Sequence[Optional[int]] = (),
) -> Tuple[List[int], Decision, bool]:
    """Execution order for an eligible join run. Static rule: smallest
    build side first (a smaller hash table probes cheaper and — on
    star schemas — correlates with selectivity). Feedback rule: once a
    previous execution observed per-join row selectivity, the most
    selective join runs first so later joins probe fewer rows.
    Returns ``(order, decision, used_stats)``."""
    n = len(build_rows)
    details: Dict[str, object] = {
        "build_rows": [int(b) for b in build_rows],
    }
    if estimates:
        details["estimated_rows"] = [
            (int(e) if e is not None else None) for e in estimates
        ]
    used_stats = all(s is not None for s in observed_sels) and n > 0
    if used_stats:
        details["observed_sel"] = [round(float(s), 4)
                                  for s in observed_sels]
        order = sorted(
            range(n),
            key=lambda i: (float(observed_sels[i]), int(build_rows[i]), i),
        )
        why = "observed per-join row selectivity (stats sidecar): " \
              "most selective join first, later joins probe fewer rows"
    else:
        order = sorted(range(n), key=lambda i: (int(build_rows[i]), i))
        why = "estimated build-side size: smallest hash table first"
    details["order"] = list(order)
    if order == list(range(n)):
        return order, Decision(
            "join_order_static",
            "recorded order already optimal by " + why, details,
        ), used_stats
    return order, Decision("reorder_joins", why, details), used_stats


def warm_segment_bucket(ops_key: tuple, counts: Sequence[int]) -> None:
    """Warm-start the segment-bucketing history from observed group
    counts (the stats sidecar): a fresh process that historically saw
    K proliferate starts bucketing on its FIRST aggregate instead of
    re-learning (and re-tracing) per distinct count."""
    with _K_LOCK:
        seen = _K_HISTORY.setdefault(ops_key, set())
        seen.update(int(c) for c in counts)


# Segment-count bucketing history: per (ops fingerprint), the distinct
# group counts recently lowered. Varying K retraces the epilogue per
# distinct count; once the history shows proliferation, round K up to
# the next power of two (results are sliced back to the true K, so the
# choice is invisible to callers).
_K_LOCK = threading.Lock()
_K_HISTORY: Dict[tuple, Set[int]] = {}
_K_HISTORY_MAX = 64


def decide_segment_bucket(
    ops_key: tuple, num_groups: int
) -> Tuple[int, Optional[Decision]]:
    """Returns ``(effective_num_segments, decision)`` — ``decision`` is
    non-None only when bucketing engaged (the caller counts it)."""
    with _K_LOCK:
        seen = _K_HISTORY.setdefault(ops_key, set())
        seen.add(int(num_groups))
        distinct = len(seen)
        if len(_K_HISTORY) > _K_HISTORY_MAX:  # bound the module state
            _K_HISTORY.pop(next(iter(_K_HISTORY)))
    if distinct < 3:
        return int(num_groups), None
    k_pad = 1
    while k_pad < num_groups:
        k_pad <<= 1
    if k_pad == num_groups:
        return int(num_groups), None
    return k_pad, Decision(
        "bucket_segments",
        f"{distinct} distinct group counts for this op set — pad "
        f"segments {num_groups}->{k_pad} so the epilogue executable "
        "is reused across counts (padded groups slice away)",
        {"num_groups": int(num_groups), "padded": int(k_pad),
         "distinct_counts": distinct},
    )
