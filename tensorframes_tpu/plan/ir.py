"""The logical-plan IR: verb chains recorded as linked nodes.

Every lazy verb on a frame appends one :class:`PlanNode` instead of
nesting another compute thunk, so at force time the whole chain is
visible at once and :mod:`.lower` can fuse each maximal run of map
stages into a single composed XLA program per block (the Flare /
HiFrames observation: operator-chain fusion dominates per-operator
execution — and XLA gives us the kernel fusion for free once the chain
is composed under one jit).

Node kinds:

* ``source`` — wraps a frame with no (live) plan: the chain's input.
* ``map`` — one map_blocks (``rows=False``) or map_rows (``rows=True``)
  stage carrying its normalized, feed_dict-renamed :class:`Program`.
* ``select`` — column projection; drives pushdown pruning in
  :mod:`.rules` so dead columns are never computed, gathered, or
  transferred.
* ``filter`` — row subsetting by the mask column its parent ``map``
  stage computes; fuses the mask program into the upstream run and
  splits the chain for downstream stages (a data-dependent row count is
  a fusion barrier by nature).
* ``join`` — a hash join whose probe (left) side is this chain;
  ``right`` holds the build-side frame (an independent plan input, so a
  strong reference) and ``spec`` the normalized join description
  (:class:`tensorframes_tpu.frame._JoinSpec`). Like ``filter`` it ends
  its segment (the output row count is data-dependent), but the
  upstream probe-side maps fuse into the probe dispatch and the
  needed-columns pass prunes THROUGH it on both sides.
* ``aggregate`` — a keyed segment-reduce epilogue: ``program`` is the
  normalized reduce program, ``keys`` the group-by columns, ``spec``
  the ``segment_reduce_info`` op list. Terminal: the lowering composes
  the upstream fused maps with the segment reduction into one Program
  per block (tree-combined across blocks), so the mapped value columns
  are never materialized.
* ``reduce`` — a whole-frame ``reduce_blocks``/``reduce_rows``
  epilogue (``spec`` is the mode string); terminal like ``aggregate``.

Nodes hold a **weak** reference to the frame they describe: if an
intermediate frame was already forced (or an internal mask frame was
collected), :func:`resolve_chain` re-roots the chain there instead of
recomputing upstream stages.

Fusion barriers that do NOT create nodes (trim maps, ``to_host``,
``repartition``, host-callback programs) mark the frames they produce
via :func:`mark_barrier`, which the TFG107 analysis rule reads through
:func:`chain_barriers`.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import List, Optional, Sequence, Tuple

__all__ = [
    "PlanNode",
    "allow_planning",
    "fusion_enabled",
    "lowering",
    "lowering_active",
    "node_for_parent",
    "resolve_chain",
    "mark_barrier",
    "mark_unfused",
    "unfused_epilogues",
    "mark_pushdown_miss",
    "pushdown_miss_log",
    "parent_is_fusable",
    "program_has_callback",
    "chain_barriers",
    "explain_plan",
]


class PlanNode:
    """One step of a logical plan (immutable after construction)."""

    __slots__ = (
        "kind",        # 'source'|'map'|'select'|'filter'|'join'|'aggregate'|'reduce'
        "parent",      # upstream PlanNode (None for source)
        "source_frame",  # kind == 'source': the wrapped frame (strong ref)
        "program",     # 'map': stage Program; 'aggregate'/'reduce': reduce Program
        "rows",        # kind == 'map': True for map_rows semantics
        "out_names",   # 'map': program outputs; 'aggregate'/'reduce': fetch names
        "names",       # kind == 'select': kept column names, in order
        "mask_name",   # kind == 'filter': the mask column (parent map's out)
        "right",       # kind == 'join': the build-side frame (strong ref)
        "spec",        # 'join': _JoinSpec; 'aggregate': seg_info; 'reduce': mode
        "keys",        # kind == 'aggregate': group-by column names
        "schema",      # result Schema of this node's frame
        "_frame_ref",  # weakref to the frame this node describes
        "_extended",   # a downstream node already chains on this one
    )

    def __init__(
        self,
        kind: str,
        parent: Optional["PlanNode"] = None,
        source_frame=None,
        program=None,
        rows: bool = False,
        out_names: Sequence[str] = (),
        names: Sequence[str] = (),
        mask_name: Optional[str] = None,
        right=None,
        spec=None,
        keys: Sequence[str] = (),
        schema=None,
    ):
        self.kind = kind
        self.parent = parent
        self.source_frame = source_frame
        self.program = program
        self.rows = rows
        self.out_names = tuple(out_names)
        self.names = tuple(names)
        self.mask_name = mask_name
        self.right = right
        self.spec = spec
        self.keys = tuple(keys)
        self.schema = schema
        self._frame_ref = None
        self._extended = False

    def bind(self, frame) -> "PlanNode":
        self._frame_ref = weakref.ref(frame)
        return self

    def frame(self):
        return self._frame_ref() if self._frame_ref is not None else None

    def __repr__(self) -> str:
        if self.kind == "map":
            verb = "map_rows" if self.rows else "map_blocks"
            return f"{verb}({', '.join(self.out_names)})"
        if self.kind == "select":
            return f"select({list(self.names)})"
        if self.kind == "filter":
            return f"filter(mask={self.mask_name!r})"
        if self.kind == "join":
            return (
                f"join(on={list(self.spec.keys)}, how={self.spec.how!r})"
            )
        if self.kind == "aggregate":
            ops = [op for _, op, _ in (self.spec or ())]
            return f"aggregate(keys={list(self.keys)}, ops={ops})"
        if self.kind == "reduce":
            return f"reduce_{self.spec}({', '.join(self.out_names)})"
        return "source"


# ---------------------------------------------------------------------------
# lowering re-entrancy guard: the lowering pass executes stages through
# the ordinary verbs, which must not re-plan while it runs
# ---------------------------------------------------------------------------

_TLS = threading.local()


def lowering_active() -> bool:
    return getattr(_TLS, "depth", 0) > 0


@contextlib.contextmanager
def lowering():
    _TLS.depth = getattr(_TLS, "depth", 0) + 1
    try:
        yield
    finally:
        _TLS.depth -= 1


@contextlib.contextmanager
def allow_planning():
    """Escape the re-entrancy guard for an INDEPENDENT chain: the
    lowering pass must not re-plan the chain it is executing, but a
    join's build side is its own pipeline — planning (and therefore
    pushdown-pruning) it is both safe and required. Restores the
    ambient depth on exit."""
    depth = getattr(_TLS, "depth", 0)
    _TLS.depth = 0
    try:
        yield
    finally:
        _TLS.depth = depth


def fusion_enabled() -> bool:
    """True when verbs should record plan nodes: the ``plan_fusion``
    knob is on (``TFTPU_FUSION=0`` is the escape hatch) and we are not
    inside the lowering pass itself."""
    from ..config import get_config

    return bool(get_config().plan_fusion) and not lowering_active()


def node_for_parent(frame) -> PlanNode:
    """The plan node a new stage should chain onto: the parent's own
    plan when it is still lazy and unbranched, else a fresh source
    wrapping the frame. The branch rule bounds duplicate work on
    DAG-shaped pipelines: the FIRST consumer extends the chain (and
    will recompute the shared prefix in-register, fused); every LATER
    consumer sources on the frame itself, so forcing it materializes
    the shared prefix exactly once (cached on the frame) instead of
    re-running it inside each branch's fused program."""
    node = getattr(frame, "_plan", None)
    if node is not None and not frame.is_materialized:
        if not node._extended:
            node._extended = True
            return node
    return PlanNode("source", source_frame=frame, schema=frame.schema)


def resolve_chain(node: PlanNode) -> Tuple[object, List[PlanNode]]:
    """Walk ``node``'s ancestry to the effective source: the first
    source node, or the first intermediate frame that has already been
    forced (its cached blocks are authoritative — recomputing upstream
    stages would be wasted work). Returns ``(source_frame, nodes)`` with
    ``nodes`` ordered source-most first, ending at ``node``."""
    nodes: List[PlanNode] = []
    cur = node
    while True:
        if cur.kind == "source":
            return cur.source_frame, list(reversed(nodes))
        f = cur.frame()
        if f is not None and f.is_materialized and nodes:
            return f, list(reversed(nodes))
        nodes.append(cur)
        cur = cur.parent


# ---------------------------------------------------------------------------
# barrier bookkeeping (read by the TFG107 analysis rule)
# ---------------------------------------------------------------------------

def parent_is_fusable(frame) -> bool:
    """True when ``frame`` came out of a (fusable) map chain — the
    'otherwise-fusable maps' half of the TFG107 condition."""
    return (
        getattr(frame, "_plan", None) is not None
        or getattr(frame, "_produced_by_map", False)
    )


def mark_barrier(frame, reason: str, parent) -> None:
    """Record that ``frame`` was produced by a fusion barrier so a later
    ``lint_plan`` can name it (TFG107). No-op semantics otherwise."""
    try:
        frame._fusion_barrier = reason
        frame._fusion_barrier_upstream = parent_is_fusable(parent)
    except AttributeError:  # pragma: no cover - exotic frame-likes
        pass


def mark_unfused(frame, verb: str, reason: str) -> None:
    """Record that ``frame`` came out of an ``aggregate``/``join`` whose
    epilogue stayed a fusion barrier for a *fusable* reason — the
    TFG109 evidence. Called at verb time for statically-knowable causes
    (non-algebraic fetches) and appended at force time for runtime ones
    (ragged value cells, a group key computed by a chained stage).
    Mandatory fallbacks (sharded / multi-process feeds) are honest, not
    fusable, and are never recorded here."""
    try:
        log = getattr(frame, "_plan_unfused", None)
        if log is None:
            log = frame._plan_unfused = []
        log.append({"verb": verb, "reason": reason})
    except AttributeError:  # pragma: no cover - exotic frame-likes
        pass


def unfused_epilogues(frame) -> List[dict]:
    """The TFG109 evidence recorded by :func:`mark_unfused` (empty when
    every epilogue fused, or nothing was recorded)."""
    return list(getattr(frame, "_plan_unfused", ()) or ())


def mark_pushdown_miss(frame, miss: dict) -> None:
    """Record that an aggregate sitting above a join missed the
    pushdown rewrite for a *fixable* cause — the TFG110 evidence.
    Static causes (order-sensitive float fetches, group keys not
    covering the join key, mixed-side columns) are recorded at force
    time from the eligibility walk; runtime causes (duplicate
    build-side keys) append when the lowering's m=1 check fails.
    Mandatory exclusions (sharded/multi-process feeds, TFTPU_REOPT=0)
    are honest, not fixable, and are never recorded here."""
    try:
        log = getattr(frame, "_plan_pushdown_miss", None)
        if log is None:
            log = frame._plan_pushdown_miss = []
        log.append(dict(miss))
    except AttributeError:  # pragma: no cover - exotic frame-likes
        pass


def pushdown_miss_log(frame) -> List[dict]:
    """The TFG110 evidence recorded by :func:`mark_pushdown_miss`."""
    return list(getattr(frame, "_plan_pushdown_miss", ()) or ())


def program_has_callback(program) -> bool:
    """True when the program's jaxpr contains a host-callback primitive
    (``pure_callback`` / ``io_callback`` / ``debug_callback`` …): such a
    stage executes per-stage so callback batching semantics stay exactly
    the single-verb ones. Cached on the Program; a trace failure is
    treated as a callback (conservative: never fuse what we cannot
    see)."""
    # a verified-lifted UDF program is pure jnp by construction — skip
    # the jaxpr walk (plan/lift primes _tftpu_has_callback too; this
    # guard keeps the invariant even if the cache attribute is lost on
    # a Program rebuild, e.g. rename_inputs)
    if getattr(program, "_tftpu_lifted", False):
        return False
    cached = getattr(program, "_tftpu_has_callback", None)
    if cached is not None:
        return cached
    try:
        import jax

        from ..program import _abstract_inputs

        closed = jax.make_jaxpr(program.fn)(
            _abstract_inputs(program.inputs, 3)
        )
        from ..analysis.rules import _iter_eqns

        has = any(
            "callback" in eqn.primitive.name for eqn in _iter_eqns(closed.jaxpr)
        )
    except Exception:
        has = True
    try:
        program._tftpu_has_callback = has
    except AttributeError:  # pragma: no cover
        pass
    return has


def chain_barriers(frame):
    """Inspect ``frame``'s plan chain for fusion barriers sitting
    between otherwise-fusable maps — the TFG107 evidence. Returns
    ``(n_map_stages, barriers)`` where each barrier is a dict with
    ``reason``, ``upstream_maps``, ``downstream_maps``. Never forces a
    lazy frame."""
    node = getattr(frame, "_plan", None)
    barriers: List[dict] = []
    if node is None:
        return 0, barriers
    source, nodes = resolve_chain(node)
    maps = [n for n in nodes if n.kind == "map"]
    # host-callback stages inside the chain split the fused run as soon
    # as they have a fusable neighbor on either side
    for i, n in enumerate(maps):
        if len(maps) >= 2 and program_has_callback(n.program):
            barriers.append({
                "reason": "host callback in "
                          + ("map_rows" if n.rows else "map_blocks")
                          + f" stage producing {list(n.out_names)}",
                "upstream_maps": i,
                "downstream_maps": len(maps) - i - 1,
            })
    # a source frame produced by a barrier op, with fusable maps both
    # upstream (recorded on the source) and downstream (this chain)
    reason = getattr(source, "_fusion_barrier", None)
    if reason and getattr(source, "_fusion_barrier_upstream", False) and maps:
        # the upstream chain's plan was dropped when the barrier forced
        # it, so only "at least one fusable map" is knowable here
        barriers.append({
            "reason": reason,
            "upstream_maps": 1,
            "upstream_exact": False,
            "downstream_maps": len(maps),
        })
    # ragged source columns feeding a fusable run execute per-stage
    # (ragged regrouping); only checkable without forcing when the
    # source is already materialized
    if len(maps) >= 2 and getattr(source, "is_materialized", False):
        try:
            from ..ops.executor import block_is_ragged

            src_names = set(source.schema.names)
            ragged_ins = sorted({
                i
                for n in maps
                for i in n.program.input_names
                if i in src_names and any(
                    block_is_ragged(b, [i]) for b in source.blocks()
                )
            })
            if ragged_ins:
                barriers.append({
                    "reason": "ragged regrouping: column(s) "
                              f"{ragged_ins} hold ragged cells",
                    "upstream_maps": 1,
                    "upstream_exact": False,
                    "downstream_maps": len(maps) - 1,
                })
        except Exception:  # pragma: no cover - lint must never raise
            pass
    return len(maps), barriers


def explain_plan(frame, analyze: bool = False) -> str:
    """Render a frame's logical plan, one node per line (source first).
    Frames without a plan render as a single ``source`` line. With
    ``analyze=True`` (EXPLAIN ANALYZE, ISSUE 17) the tree is followed
    by the per-stage profile the plan's last execution recorded into
    the stats sidecar — wall, rows, bytes, chosen strategy, compile
    split — plus observed join selectivities, pushdown history, and
    TFG-diagnostic cross-references (rendered by
    ``observability/profile.py``)."""
    node = getattr(frame, "_plan", None)
    if node is None:
        state = "materialized" if frame.is_materialized else "lazy"
        lines = [f"source ({state}, {len(frame.schema.names)} column(s))"]
    else:
        source, nodes = resolve_chain(node)
        lines = [
            "source ("
            + ("materialized" if source.is_materialized else "lazy")
            + f", columns={list(source.schema.names)})"
        ]
        for n in nodes:
            lines.append(f"  -> {n!r}")
    if analyze:
        from ..observability import profile as _profile

        lines.append("")
        lines.extend(_profile.profile_lines(frame))
    return "\n".join(lines)
